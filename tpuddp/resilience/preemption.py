"""Preemption-safe training — the SIGTERM/SIGINT drain path.

HTCondor (the reference's scheduler, submit_job.py) and preemptible TPU pods
both deliver SIGTERM, wait a grace window, then SIGKILL.  The contract here:

1. :func:`install_preemption_handler` (called by ``spawn.run_ddp_training``
   and the managed entrypoint) registers handlers that only *set a flag* —
   signal handlers must not run collectives or touch XLA.
2. The epoch driver polls :func:`preemption_requested` at batch-group
   boundaries, writes an emergency checkpoint through the existing atomic
   ``checkpoint.save()`` (params + optimizer state + epoch + sampler epoch +
   RNG state travel inside the TrainState; the epoch lands in the checkpoint's
   meta record), and raises :class:`TrainingPreempted`.
3. ``spawn.run_ddp_training`` converts that into ``sys.exit(EXIT_PREEMPTED)``
   — exit code 75 (BSD ``EX_TEMPFAIL``), the "requeue me" signal schedulers
   understand.
4. A daemon failsafe timer started at signal time force-exits with the same
   code after ``$TPUDDP_PREEMPT_GRACE`` seconds (default 25), so a drain that
   wedges (e.g. a collective that never completes) still beats the SIGKILL
   and still reports the distinct code.

A second signal during the drain exits immediately: the operator (or the
scheduler escalating) asked twice.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Optional

logger = logging.getLogger("tpuddp")

# Exit-code contract (README "Fault tolerance"). 75 = EX_TEMPFAIL, the
# conventional "transient, requeue" code; 76/77/113 are tpuddp-specific but
# chosen outside the shell/signal ranges (126-165) and common tool codes.
EXIT_PREEMPTED = 75  # drained after SIGTERM/SIGINT; safe to requeue + resume
EXIT_WATCHDOG = 76  # a peer's heartbeat went stale; this process bailed out
EXIT_DESYNC = 77  # the guard's auditor found a divergent replica; requeue
# into auto-resume (resilience/guard.py — raised as ReplicaDesync)
EXIT_INJECTED_CRASH = 113  # $TPUDDP_FAULT crash@... fired (chaos tests only)

_GRACE_ENV = "TPUDDP_PREEMPT_GRACE"
_DEFAULT_GRACE = 25.0
_AUTO_RESUME_ENV = "TPUDDP_AUTO_RESUME"

_flag = threading.Event()
_state = {
    "installed": False,
    "previous": {},  # signum -> previous handler
    "signum": None,
    "deadline": None,
    "failsafe": None,
}


class TrainingPreempted(Exception):
    """Raised by the epoch driver after a successful emergency save.

    ``epoch`` is the epoch that was interrupted (resume restarts it);
    ``checkpoint`` is the emergency checkpoint path on process 0, None
    elsewhere (or when no save_dir was configured).
    """

    def __init__(self, epoch: int, checkpoint: Optional[str] = None):
        self.epoch = epoch
        self.checkpoint = checkpoint
        super().__init__(
            f"training preempted during epoch {epoch}"
            + (f"; emergency checkpoint at {checkpoint}" if checkpoint else "")
        )


def auto_resume_requested() -> bool:
    """The scheduler-requeue contract: ``$TPUDDP_AUTO_RESUME`` truthy (any
    value but empty/"0") asks the run to restore the newest intact checkpoint
    at loop entry. One parser for both entrypoints."""
    return os.environ.get(_AUTO_RESUME_ENV, "") not in ("", "0")


def preemption_grace_seconds() -> float:
    """The SIGTERM->forced-exit drain budget ($TPUDDP_PREEMPT_GRACE, s)."""
    raw = os.environ.get(_GRACE_ENV, "")
    try:
        return float(raw) if raw else _DEFAULT_GRACE
    except ValueError:
        logger.warning("ignoring non-numeric %s=%r", _GRACE_ENV, raw)
        return _DEFAULT_GRACE


def _failsafe(grace: float) -> None:
    time.sleep(grace)
    if _flag.is_set():  # drain did not finish in time; beat the SIGKILL
        logger.critical(
            "preemption drain exceeded the %.0fs grace window; forcing exit %d",
            grace,
            EXIT_PREEMPTED,
        )
        try:
            # a drain that WEDGED is exactly when a post-mortem matters:
            # dump whatever the flight rings hold before the forced exit
            # skips every finally. BOUNDED by contract: the dump writes to
            # the same (possibly wedged) shared filesystem the drain hung
            # on, and a hang is not an exception — so it runs on a daemon
            # side thread with a short join, and the forced exit proceeds
            # regardless. The failsafe's whole job is to beat the SIGKILL;
            # it must never trade that for a post-mortem.
            from tpuddp.observability import flight

            t = threading.Thread(
                target=flight.dump_all,
                args=("preempt_forced",),
                name="tpuddp-flight-forced",
                daemon=True,
            )
            t.start()
            t.join(timeout=5.0)
            if t.is_alive():
                logger.critical(
                    "forced-exit flight dump is wedged too (shared FS?); "
                    "exiting without it"
                )
        except Exception:
            logger.exception("forced-exit flight dump failed")
        os._exit(EXIT_PREEMPTED)


def request_preemption(signum: Optional[int] = None, frame=None) -> None:
    """The handler body (also callable directly, e.g. by fault injection):
    set the flag, arm the grace-window failsafe, never touch the runtime."""
    if _flag.is_set():
        # second signal: the scheduler/operator escalated — exit now
        logger.critical("second preemption signal; exiting immediately")
        os._exit(EXIT_PREEMPTED)
    grace = preemption_grace_seconds()
    _flag.set()
    _state["signum"] = signum
    _state["deadline"] = time.monotonic() + grace
    name = signal.Signals(signum).name if signum is not None else "request"
    logger.warning(
        "%s received: draining — emergency checkpoint at the next batch-group "
        "boundary, then exit %d (grace %.0fs)",
        name,
        EXIT_PREEMPTED,
        grace,
    )
    t = threading.Thread(
        target=_failsafe, args=(grace,), name="tpuddp-preempt-failsafe", daemon=True
    )
    t.start()
    _state["failsafe"] = t


def preemption_requested() -> bool:
    return _flag.is_set()


def preemption_deadline() -> Optional[float]:
    """``time.monotonic()`` deadline of the drain window, None if not draining."""
    return _state["deadline"] if _flag.is_set() else None


def install_preemption_handler(signals=(signal.SIGTERM, signal.SIGINT)) -> bool:
    """Register the drain handlers. Main-thread only (a Python limitation);
    returns False (and stays a no-op) elsewhere, e.g. under a test runner
    driving workers from helper threads."""
    if threading.current_thread() is not threading.main_thread():
        logger.debug("not main thread; preemption handler not installed")
        return False
    if _state["installed"]:
        return True
    for s in signals:
        _state["previous"][s] = signal.signal(s, request_preemption)
    _state["installed"] = True
    return True


def uninstall_preemption_handler() -> None:
    if not _state["installed"]:
        return
    for s, prev in _state["previous"].items():
        signal.signal(s, prev)
    _state["previous"].clear()
    _state["installed"] = False


def reset_preemption() -> None:
    """Clear the flag/deadline (test isolation; a real process exits instead)."""
    _flag.clear()
    _state.update(signum=None, deadline=None, failsafe=None)
