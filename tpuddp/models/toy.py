"""Toy models for fast CI and the toy-MLP BASELINE configs.

``BASELINE.json`` names a "toy MLP" and a "toy CNN with SyncBatchNorm"; the
reference itself has no toy model (its ``load_model`` is AlexNet,
data_and_toy_model.py:41-45), so these are the genuinely-small CI models
SURVEY.md's scale calibration calls for.
"""

from __future__ import annotations

from tpuddp import nn


def ToyMLP(num_classes: int = 10, hidden=(256, 128)) -> nn.Sequential:
    """Flatten -> [Linear -> ReLU]* -> Linear head. Input: any NHWC image."""
    layers = [nn.Flatten()]
    for h in hidden:
        layers += [nn.Linear(h), nn.ReLU()]
    layers.append(nn.Linear(num_classes))
    return nn.Sequential(*layers)


def ToyCNN(num_classes: int = 10, widths=(32, 64), sync_bn: bool = False) -> nn.Sequential:
    """Conv -> BN -> ReLU -> MaxPool blocks + linear head. With
    ``sync_bn=True`` (or convert_sync_batchnorm later), batch statistics are
    pmean'd across the data axis — the SyncBatchNorm BASELINE config."""
    layers = []
    for w in widths:
        layers += [
            # no conv bias before BN: BN cancels shifts, so a bias's gradient is
            # pure float noise, which Adam would amplify nondeterministically
            nn.Conv2d(w, kernel_size=3, padding=1, use_bias=False),
            nn.BatchNorm(sync=sync_bn),
            nn.ReLU(),
            nn.MaxPool2d(2),
        ]
    layers += [nn.Flatten(), nn.Linear(num_classes)]
    return nn.Sequential(*layers)
