"""AlexNet-class CNN — the reference's flagship model.

The reference loads torchvision's pretrained AlexNet and swaps the last
classifier layer for CIFAR-10 (data_and_toy_model.py:41-45). This is the same
architecture in NHWC (TPU-native layout), trained from scratch: pretrained
ImageNet weights are a torchvision download and this build runs zero-egress.
``classifier_head_only=False`` + :func:`replace_head` reproduce the
swap-the-head workflow for any weights loaded from disk.
"""

from __future__ import annotations

import jax

from tpuddp import nn


def AlexNet(
    num_classes: int = 10, dropout: float = 0.5, space_to_depth: bool = False
) -> nn.Sequential:
    """torchvision AlexNet topology: 5 conv blocks -> adaptive 6x6 avg pool ->
    3-layer classifier. Input is NHWC, any spatial size >= 63 (reference feeds
    224x224 CIFAR upsamples).

    ``space_to_depth=True`` swaps the 11x11/s4 3-channel stem for its exact
    space-to-depth reparameterization (nn.SpaceToDepthConv2d) — same math,
    same parameter shapes (checkpoints/torch imports interchangeable), far
    better MXU utilization on the thin-channel strided stem."""
    stem_cls = nn.SpaceToDepthConv2d if space_to_depth else nn.Conv2d
    features = [
        stem_cls(64, kernel_size=11, strides=4, padding=2),
        nn.ReLU(),
        nn.MaxPool2d(3, strides=2),
        nn.Conv2d(192, kernel_size=5, padding=2),
        nn.ReLU(),
        nn.MaxPool2d(3, strides=2),
        nn.Conv2d(384, kernel_size=3, padding=1),
        nn.ReLU(),
        nn.Conv2d(256, kernel_size=3, padding=1),
        nn.ReLU(),
        nn.Conv2d(256, kernel_size=3, padding=1),
        nn.ReLU(),
        nn.MaxPool2d(3, strides=2),
    ]
    classifier = [
        nn.AdaptiveAvgPool2d((6, 6)),
        nn.Flatten(),
        nn.Dropout(dropout),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Dropout(dropout),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Linear(num_classes),
    ]
    return nn.Sequential(*features, *classifier)


def replace_head(model: nn.Sequential, params, key, num_classes: int):
    """Swap the final Linear's parameters for a fresh ``num_classes`` head —
    the reference's ``model.classifier[6] = nn.Linear(4096, 10)`` move
    (data_and_toy_model.py:43-44). Returns updated params."""
    head: nn.Linear = model[-1]
    in_features = params[-1]["weight"].shape[0]
    new_head = nn.Linear(num_classes, use_bias=head.use_bias)
    new_p, _ = new_head.init(key, jax.ShapeDtypeStruct((1, in_features), params[-1]["weight"].dtype))
    model.layers = model.layers[:-1] + (new_head,)
    return tuple(params[:-1]) + (new_p,)
