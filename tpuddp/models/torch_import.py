"""Import torch/torchvision AlexNet weights into tpuddp's AlexNet.

The reference starts from *pretrained* torchvision AlexNet weights
(data_and_toy_model.py:41-43). This build runs zero-egress, so pretrained
weights can't be downloaded — but when a torchvision ``state_dict`` exists on
disk (or any torch AlexNet checkpoint), this converter maps it into tpuddp's
NHWC parameter tree:

- conv weights:   OIHW -> HWIO transpose;
- first classifier Linear: torch flattens NCHW (c, h, w) while tpuddp flattens
  NHWC (h, w, c), so the 9216-dim input axis is re-ordered accordingly;
- other Linears:  (out, in) -> (in, out) transpose.

The conversion is validated end-to-end in tests: a torch AlexNet and the
imported tpuddp AlexNet produce matching logits — the strongest available
proof that the architectures are identical.
"""

from __future__ import annotations

from typing import Dict, Mapping

import jax.numpy as jnp
import numpy as np

# torchvision AlexNet state_dict key -> index of the layer in tpuddp's
# Sequential (tpuddp/models/alexnet.py). Conveniently torchvision's
# features.N indices coincide with ours because the layer order is identical.
_CONV_KEYS = {
    "features.0": 0,
    "features.3": 3,
    "features.6": 6,
    "features.8": 8,
    "features.10": 10,
}
_LINEAR_KEYS = {
    # layer indices in tpuddp's 22-layer Sequential: features occupy 0-12
    # (last MaxPool at 12), then AdaptiveAvgPool@13, Flatten@14, Dropout@15,
    # Linear@16, ReLU@17, Dropout@18, Linear@19, ReLU@20, Linear@21
    "classifier.1": 16,
    "classifier.4": 19,
    "classifier.6": 21,
}
_POOL_GRID = 6  # AdaptiveAvgPool2d((6, 6))
_POOL_CH = 256


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def convert_alexnet_state_dict(state_dict: Mapping[str, object], params):
    """Return a copy of tpuddp AlexNet ``params`` (tuple pytree from
    ``AlexNet().init``) with weights replaced by the torch ``state_dict``."""
    new_params = list(params)

    for key, idx in _CONV_KEYS.items():
        w = _to_np(state_dict[f"{key}.weight"])  # OIHW
        b = _to_np(state_dict[f"{key}.bias"])
        hwio = np.transpose(w, (2, 3, 1, 0))
        expect = new_params[idx]["weight"].shape
        if hwio.shape != tuple(expect):
            raise ValueError(f"{key}: shape {hwio.shape} != expected {expect}")
        new_params[idx] = {"weight": jnp.asarray(hwio), "bias": jnp.asarray(b)}

    for key, idx in _LINEAR_KEYS.items():
        w = _to_np(state_dict[f"{key}.weight"])  # (out, in)
        b = _to_np(state_dict[f"{key}.bias"])
        if key == "classifier.1":
            # re-order the flattened input axis: torch (c, h, w) -> ours (h, w, c)
            out_f = w.shape[0]
            w = (
                w.reshape(out_f, _POOL_CH, _POOL_GRID, _POOL_GRID)
                .transpose(2, 3, 1, 0)  # -> (h, w, c, out)
                .reshape(_POOL_GRID * _POOL_GRID * _POOL_CH, out_f)
            )
        else:
            w = w.T
        expect = new_params[idx]["weight"].shape
        if w.shape != tuple(expect):
            raise ValueError(f"{key}: shape {w.shape} != expected {expect}")
        new_params[idx] = {"weight": jnp.asarray(w), "bias": jnp.asarray(b)}

    return tuple(new_params)


def load_torch_alexnet(params, path: str):
    """Load a torch ``.pt``/``.pth`` AlexNet state_dict from ``path`` and
    convert. Requires torch at call time (it is a dev/test dependency only)."""
    import torch

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(state_dict, "state_dict"):
        state_dict = state_dict.state_dict()
    return convert_alexnet_state_dict(state_dict, params)


def load_pretrained_alexnet(
    path: str, key, num_classes: int = 10, image_size: int = 224
):
    """The reference's fine-tune-from-pretrained workflow
    (data_and_toy_model.py:41-45), from a torch checkpoint on disk: build an
    AlexNet sized to the checkpoint's own head (e.g. 1000-class ImageNet),
    import the weights, then swap in a fresh ``num_classes`` head when the
    widths differ. Returns ``(model, params, model_state)`` ready for
    ``DistributedDataParallel.init_state`` / ``Accelerator.prepare``.
    """
    import jax
    import torch

    from tpuddp.models.alexnet import AlexNet, replace_head

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(state_dict, "state_dict"):
        state_dict = state_dict.state_dict()
    head_out = int(_to_np(state_dict["classifier.6.weight"]).shape[0])

    model = AlexNet(num_classes=head_out)
    init_key, head_key = jax.random.split(jax.random.fold_in(key, 0x9e7))
    params, model_state = model.init(
        init_key, jnp.zeros((1, image_size, image_size, 3))
    )
    params = convert_alexnet_state_dict(state_dict, params)
    if head_out != num_classes:
        params = replace_head(model, params, head_key, num_classes)
    return model, params, model_state


def pretrained_from_config(training: Mapping[str, object], key=None):
    """Entrypoint-shared ``training.pretrained_path`` handling: validate the
    model name, derive the head-init key from ``training.seed`` when the
    caller has no rank-seeded stream, and load. Returns
    ``(model, params, model_state)``."""
    import jax

    if training["model"] != "alexnet":
        raise ValueError(
            "training.pretrained_path supports model 'alexnet' "
            f"(got {training['model']!r})"
        )
    if key is None:
        key = jax.random.key(int(training.get("seed") or 0))
    from tpuddp.config import num_classes_from

    return load_pretrained_alexnet(
        str(training["pretrained_path"]),
        key,
        num_classes=num_classes_from(training),
        image_size=int(training.get("image_size") or 224),
    )
