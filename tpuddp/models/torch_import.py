"""Import torch/torchvision checkpoints into tpuddp models (AlexNet,
VGG-11/13/16/19, ResNet-18/34/50/101/152).

The reference starts from *pretrained* torchvision AlexNet weights
(data_and_toy_model.py:41-43). This build runs zero-egress, so pretrained
weights can't be downloaded — but when a torchvision ``state_dict`` exists on
disk (or any torch AlexNet checkpoint), this converter maps it into tpuddp's
NHWC parameter tree:

- conv weights:   OIHW -> HWIO transpose;
- first classifier Linear: torch flattens NCHW (c, h, w) while tpuddp flattens
  NHWC (h, w, c), so the 9216-dim input axis is re-ordered accordingly;
- other Linears:  (out, in) -> (in, out) transpose.

The conversion is validated end-to-end in tests: a torch AlexNet and the
imported tpuddp AlexNet produce matching logits — the strongest available
proof that the architectures are identical.
"""

from __future__ import annotations

from functools import partial as _pt
from typing import Dict, Mapping

import jax.numpy as jnp
import numpy as np

# torchvision AlexNet state_dict key -> index of the layer in tpuddp's
# Sequential (tpuddp/models/alexnet.py). Conveniently torchvision's
# features.N indices coincide with ours because the layer order is identical.
_CONV_KEYS = {
    "features.0": 0,
    "features.3": 3,
    "features.6": 6,
    "features.8": 8,
    "features.10": 10,
}
_LINEAR_KEYS = {
    # layer indices in tpuddp's 22-layer Sequential: features occupy 0-12
    # (last MaxPool at 12), then AdaptiveAvgPool@13, Flatten@14, Dropout@15,
    # Linear@16, ReLU@17, Dropout@18, Linear@19, ReLU@20, Linear@21
    "classifier.1": 16,
    "classifier.4": 19,
    "classifier.6": 21,
}
_POOL_GRID = 6  # AdaptiveAvgPool2d((6, 6))
_POOL_CH = 256


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, dtype=np.float32)


def _convert_seq_cnn(
    state_dict: Mapping[str, object],
    params,
    conv_keys: Mapping[str, int],
    linear_keys: Mapping[str, int],
    first_linear: str,
    pool_grid: int,
    pool_ch: int,
):
    """Shared torchvision-Sequential-CNN converter (AlexNet, VGG): conv OIHW
    -> HWIO; the FIRST classifier Linear's flattened input axis is re-ordered
    from torch's NCHW flatten (c, h, w) to NHWC (h, w, c); other Linears are
    plain transposes. Every tensor's shape is validated with the torch key
    named on mismatch."""
    new_params = list(params)

    for key, idx in conv_keys.items():
        w = _to_np(state_dict[f"{key}.weight"])  # OIHW
        b = _to_np(state_dict[f"{key}.bias"])
        hwio = np.transpose(w, (2, 3, 1, 0))
        expect = new_params[idx]["weight"].shape
        if hwio.shape != tuple(expect):
            raise ValueError(f"{key}: shape {hwio.shape} != expected {expect}")
        new_params[idx] = {"weight": jnp.asarray(hwio), "bias": jnp.asarray(b)}

    for key, idx in linear_keys.items():
        w = _to_np(state_dict[f"{key}.weight"])  # (out, in)
        b = _to_np(state_dict[f"{key}.bias"])
        if key == first_linear:
            # re-order the flattened input axis: torch (c, h, w) -> ours (h, w, c)
            out_f = w.shape[0]
            w = (
                w.reshape(out_f, pool_ch, pool_grid, pool_grid)
                .transpose(2, 3, 1, 0)  # -> (h, w, c, out)
                .reshape(pool_grid * pool_grid * pool_ch, out_f)
            )
        else:
            w = w.T
        expect = new_params[idx]["weight"].shape
        if w.shape != tuple(expect):
            raise ValueError(f"{key}: shape {w.shape} != expected {expect}")
        new_params[idx] = {"weight": jnp.asarray(w), "bias": jnp.asarray(b)}

    return tuple(new_params)


def convert_alexnet_state_dict(state_dict: Mapping[str, object], params):
    """Return a copy of tpuddp AlexNet ``params`` (tuple pytree from
    ``AlexNet().init``) with weights replaced by the torch ``state_dict``."""
    return _convert_seq_cnn(
        state_dict, params, _CONV_KEYS, _LINEAR_KEYS,
        first_linear="classifier.1", pool_grid=_POOL_GRID, pool_ch=_POOL_CH,
    )


def convert_vgg_state_dict(name: str, state_dict: Mapping[str, object], params):
    """torchvision-layout VGG ``state_dict`` -> tpuddp VGG params. The
    ``features.N`` conv index map and the classifier Linear positions are
    GENERATED from the same plan that builds the tpuddp model
    (tpuddp/models/vgg.py), so the correspondence can't drift."""
    from tpuddp.models.vgg import vgg_classifier_linear_indices, vgg_conv_indices

    conv_keys = {f"features.{i}": i for i in vgg_conv_indices(name)}
    l0, l1, l2 = vgg_classifier_linear_indices(name)
    linear_keys = {"classifier.0": l0, "classifier.3": l1, "classifier.6": l2}
    return _convert_seq_cnn(
        state_dict, params, conv_keys, linear_keys,
        first_linear="classifier.0", pool_grid=7, pool_ch=512,
    )


def load_torch_alexnet(params, path: str):
    """Load a torch ``.pt``/``.pth`` AlexNet state_dict from ``path`` and
    convert. Requires torch at call time (it is a dev/test dependency only)."""
    import torch

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(state_dict, "state_dict"):
        state_dict = state_dict.state_dict()
    return convert_alexnet_state_dict(state_dict, params)


def load_pretrained_alexnet(
    path: str, key, num_classes: int = 10, image_size: int = 224,
    space_to_depth: bool = False,
):
    """The reference's fine-tune-from-pretrained workflow
    (data_and_toy_model.py:41-45), from a torch checkpoint on disk: build an
    AlexNet sized to the checkpoint's own head (e.g. 1000-class ImageNet),
    import the weights, then swap in a fresh ``num_classes`` head when the
    widths differ. Returns ``(model, params, model_state)`` ready for
    ``DistributedDataParallel.init_state`` / ``Accelerator.prepare``.
    ``space_to_depth`` builds the s2d-stem variant — the parameter layout is
    identical, so the same checkpoint loads either way.
    """
    from tpuddp.models.alexnet import AlexNet

    return _load_pretrained(
        path, key, num_classes, image_size,
        build=lambda n: AlexNet(num_classes=n, space_to_depth=space_to_depth),
        head_weight_key="classifier.6.weight",
        convert=lambda sd, p, s: (convert_alexnet_state_dict(sd, p), s),
        salt=0x9e7,
    )


def _load_pretrained(
    path, key, num_classes, image_size, build, head_weight_key, convert, salt
):
    """Shared fine-tune loader: torch.load + module unwrap + build the model
    sized to the checkpoint's own head + convert + swap the head when widths
    differ. One implementation for every architecture-specific converter."""
    import jax
    import torch

    from tpuddp.models.alexnet import replace_head

    state_dict = torch.load(path, map_location="cpu", weights_only=True)
    if hasattr(state_dict, "state_dict"):
        state_dict = state_dict.state_dict()
    head_out = int(_to_np(state_dict[head_weight_key]).shape[0])

    model = build(head_out)
    init_key, head_key = jax.random.split(jax.random.fold_in(key, salt))
    params, model_state = model.init(
        init_key, jnp.zeros((1, image_size, image_size, 3))
    )
    params, model_state = convert(state_dict, params, model_state)
    if head_out != num_classes:
        params = replace_head(model, params, head_key, num_classes)
    return model, params, model_state


def _conv_w(sd, key):
    return jnp.asarray(np.transpose(_to_np(sd[f"{key}.weight"]), (2, 3, 1, 0)))


def _bn(sd, key):
    params = {
        "scale": jnp.asarray(_to_np(sd[f"{key}.weight"])),
        "bias": jnp.asarray(_to_np(sd[f"{key}.bias"])),
    }
    state = {
        "mean": jnp.asarray(_to_np(sd[f"{key}.running_mean"])),
        "var": jnp.asarray(_to_np(sd[f"{key}.running_var"])),
    }
    return params, state


def _checked(tag: str, new: Dict, expect) -> Dict:
    """Validate EVERY imported tensor's shape against the initialized tree
    before assignment — a width-variant or truncated checkpoint must fail
    here with a named tensor, not deep inside XLA at first apply."""
    for k, arr in new.items():
        if isinstance(arr, dict):
            exp_sub = expect.get(k) if isinstance(expect, dict) else None
            if exp_sub is None:
                raise ValueError(f"{tag}.{k}: unexpected parameter group")
            _checked(f"{tag}.{k}", arr, exp_sub)
            continue
        exp = expect.get(k) if isinstance(expect, dict) else None
        if exp is None or tuple(arr.shape) != tuple(exp.shape):
            raise ValueError(
                f"{tag}.{k}: shape {tuple(arr.shape)} != expected "
                f"{None if exp is None else tuple(exp.shape)}"
            )
    return new


def _recording(state_dict: Mapping[str, object]):
    """Wrap a ``state_dict`` so every key READ is recorded; returns
    ``(mapping, consumed_set)``. Together with :func:`_check_leftover` this
    enforces strictness in the checkpoint->model direction: a converter
    must touch every checkpoint tensor or the import is refused."""
    consumed: set = set()

    class _Recording(dict):
        def __getitem__(self, k):
            consumed.add(k)
            return dict.__getitem__(self, k)

    return _Recording(state_dict), consumed


def _check_leftover(state_dict, consumed, layout: str) -> None:
    leftover = sorted(
        k for k in state_dict
        if k not in consumed and not k.endswith("num_batches_tracked")
    )
    if leftover:
        raise ValueError(
            f"checkpoint has {len(leftover)} tensors this {layout} layout "
            f"does not consume (e.g. {leftover[:3]}); wrong architecture?"
        )


def _convert_resnet_state_dict(
    state_dict: Mapping[str, object], params, model_state, depths, n_convs: int
):
    """Shared torchvision-layout ResNet converter (conv1/bn1 stem,
    layer{1-4}.{block}.conv{1..n_convs}/bn{1..n_convs} (+downsample), fc)
    onto tpuddp's full-stem ResNet Sequential (tpuddp/models/resnet.py).
    ``n_convs=2`` is the BasicBlock family (ResNet-18/34), ``n_convs=3`` the
    Bottleneck family (ResNet-50). Returns ``(params, model_state)`` — unlike
    AlexNet, ResNet carries BatchNorm running statistics in the model state,
    which must ride along for eval-mode parity. Strictness both ways: every
    tensor the model expects must be in the checkpoint, and every checkpoint
    tensor must be consumed."""
    state_dict, consumed = _recording(state_dict)
    new_p, new_s = list(params), list(model_state)
    # stem: Sequential[0]=Conv2d(64,7,s2), [1]=BatchNorm ([2] ReLU, [3] MaxPool)
    new_p[0] = _checked("conv1", {"weight": _conv_w(state_dict, "conv1")}, new_p[0])
    bn_p, bn_s = _bn(state_dict, "bn1")
    new_p[1] = _checked("bn1", bn_p, new_p[1])
    new_s[1] = _checked("bn1(state)", bn_s, new_s[1])
    idx = 4  # first block index in the full-stem Sequential
    for stage, n_blocks in zip((1, 2, 3, 4), depths):
        for block in range(n_blocks):
            t = f"layer{stage}.{block}"
            p, s = {}, {}
            for i in range(1, n_convs + 1):
                p[f"conv{i}"] = {"weight": _conv_w(state_dict, f"{t}.conv{i}")}
                p[f"bn{i}"], s[f"bn{i}"] = _bn(state_dict, f"{t}.bn{i}")
            if f"{t}.downsample.0.weight" in state_dict:
                p["down_conv"] = {"weight": _conv_w(state_dict, f"{t}.downsample.0")}
                p["down_bn"], s["down_bn"] = _bn(state_dict, f"{t}.downsample.1")
            missing = (set(new_p[idx]) - set(p)) | (set(new_s[idx]) - set(s))
            if missing:
                raise ValueError(
                    f"{t}: checkpoint lacks expected tensors {sorted(missing)} "
                    "(truncated file or a different shortcut variant)"
                )
            new_p[idx] = _checked(t, p, new_p[idx])
            new_s[idx] = _checked(f"{t}(state)", s, new_s[idx])
            idx += 1
    # head: GAP at -2 (no params), Linear at -1
    w = _to_np(state_dict["fc.weight"]).T
    b = _to_np(state_dict["fc.bias"])
    if w.shape != tuple(new_p[-1]["weight"].shape):
        raise ValueError(f"fc: shape {w.shape} != {new_p[-1]['weight'].shape}")
    new_p[-1] = {"weight": jnp.asarray(w), "bias": jnp.asarray(b)}
    # Unconsumed tensors mean the checkpoint is a DIFFERENT architecture
    # whose early blocks happen to be shape-compatible (e.g. a ResNet-34
    # imported as ResNet-18 would silently drop half its blocks).
    _check_leftover(
        state_dict, consumed, f"ResNet{depths} ({n_convs}-conv block)"
    )
    return tuple(new_p), tuple(new_s)


def convert_resnet_basic_state_dict(
    state_dict: Mapping[str, object], params, model_state, depths=(2, 2, 2, 2)
):
    """BasicBlock-family converter — (2,2,2,2) is ResNet-18, (3,4,6,3) is
    ResNet-34."""
    return _convert_resnet_state_dict(state_dict, params, model_state, depths, 2)


def convert_resnet_bottleneck_state_dict(
    state_dict: Mapping[str, object], params, model_state, depths=(3, 4, 6, 3)
):
    """Bottleneck-family converter — (3,4,6,3) is ResNet-50, (3,4,23,3)
    ResNet-101, (3,8,36,3) ResNet-152."""
    return _convert_resnet_state_dict(state_dict, params, model_state, depths, 3)


def convert_resnet18_state_dict(state_dict: Mapping[str, object], params, model_state):
    """ResNet-18 ([2,2,2,2]) instantiation of the BasicBlock converter."""
    return convert_resnet_basic_state_dict(
        state_dict, params, model_state, depths=(2, 2, 2, 2)
    )


def convert_resnet34_state_dict(state_dict: Mapping[str, object], params, model_state):
    """ResNet-34 ([3,4,6,3]) instantiation of the BasicBlock converter."""
    return convert_resnet_basic_state_dict(
        state_dict, params, model_state, depths=(3, 4, 6, 3)
    )


def load_pretrained_resnet18(
    path: str, key, num_classes: int = 10, image_size: int = 224,
    space_to_depth: bool = False,
):
    """ResNet-18 analog of :func:`load_pretrained_alexnet`: build the model
    sized to the checkpoint's own head, import weights + BN statistics, swap
    in a fresh ``num_classes`` head when the widths differ."""
    from tpuddp.models.resnet import ResNet18

    return _load_pretrained(
        path, key, num_classes, image_size,
        build=lambda n: ResNet18(num_classes=n, space_to_depth=space_to_depth),
        head_weight_key="fc.weight",
        convert=convert_resnet18_state_dict,
        salt=0x9e8,
    )


def load_pretrained_resnet34(
    path: str, key, num_classes: int = 10, image_size: int = 224,
    space_to_depth: bool = False,
):
    """ResNet-34 analog of :func:`load_pretrained_resnet18` — the [3,4,6,3]
    BasicBlock depths; wrong-depth checkpoints are rejected by the block
    consumption check (missing tensors) or leftover-tensor check."""
    from tpuddp.models.resnet import ResNet34

    return _load_pretrained(
        path, key, num_classes, image_size,
        build=lambda n: ResNet34(num_classes=n, space_to_depth=space_to_depth),
        head_weight_key="fc.weight",
        convert=convert_resnet34_state_dict,
        salt=0x9e9,
    )


def _load_pretrained_bottleneck(name, cls_name, depths, salt):
    """Build the fine-tune loader for one Bottleneck family member (the
    ResNet-50/101/152 analog of :func:`load_pretrained_resnet18`)."""

    def loader(path, key, num_classes=10, image_size=224, space_to_depth=False):
        from tpuddp.models import resnet as resnet_lib

        cls = getattr(resnet_lib, cls_name)
        return _load_pretrained(
            path, key, num_classes, image_size,
            build=lambda n: cls(num_classes=n, space_to_depth=space_to_depth),
            head_weight_key="fc.weight",
            convert=_pt(convert_resnet_bottleneck_state_dict, depths=depths),
            salt=salt,
        )

    loader.__name__ = loader.__qualname__ = f"load_pretrained_{name}"
    loader.__doc__ = (
        f"{cls_name} fine-tune loader — {list(depths)} Bottleneck blocks "
        "(2048-wide head); torchvision-layout checkpoints, head swapped to "
        "``num_classes`` when the widths differ."
    )
    return loader


load_pretrained_resnet50 = _load_pretrained_bottleneck(
    "resnet50", "ResNet50", (3, 4, 6, 3), 0x9eb
)
load_pretrained_resnet101 = _load_pretrained_bottleneck(
    "resnet101", "ResNet101", (3, 4, 23, 3), 0x9ec
)
load_pretrained_resnet152 = _load_pretrained_bottleneck(
    "resnet152", "ResNet152", (3, 8, 36, 3), 0x9ed
)


def load_pretrained_vgg(
    name: str, path: str, key, num_classes: int = 10, image_size: int = 224
):
    """VGG analog of :func:`load_pretrained_alexnet`: build the model sized
    to the checkpoint's own head, import, swap in a fresh ``num_classes``
    head when the widths differ."""
    from tpuddp.models import vgg as vgg_lib

    build_cls = {
        "vgg11": vgg_lib.VGG11, "vgg13": vgg_lib.VGG13,
        "vgg16": vgg_lib.VGG16, "vgg19": vgg_lib.VGG19,
    }[name]
    return _load_pretrained(
        path, key, num_classes, image_size,
        build=lambda n: build_cls(num_classes=n),
        head_weight_key="classifier.6.weight",
        convert=lambda sd, p, s: (convert_vgg_state_dict(name, sd, p), s),
        salt=0x9ea,
    )


def convert_transformer_state_dict(state_dict: Mapping[str, object], params):
    """torch decoder-only transformer ``state_dict`` -> tpuddp
    :class:`~tpuddp.models.transformer.TransformerLM` params.

    Expected torch naming (the layout the parity test's reference module
    uses — plain Linears, not ``nn.MultiheadAttention``, so the math is
    explicit): ``embed.weight``, ``pos.weight``, per block ``blocks.{i}.
    {ln1,ln2}.{weight,bias}``, ``blocks.{i}.attn.{in_proj,out_proj}.
    {weight,bias}``, ``blocks.{i}.mlp.{fc1,fc2}.{weight,bias}``, and
    ``ln_f.{weight,bias}``. Linear weights transpose ``(out, in) -> (in,
    out)``; the joined ``in_proj`` packs ``[q; k; v]`` row blocks exactly as
    tpuddp's ``wqkv`` packs them column-wise, so the transpose alone aligns
    the ``joined_kv`` axis. The LM head is TIED to ``embed.weight`` on both
    sides — a checkpoint with a separate ``head.weight`` is a different
    architecture and is rejected by the leftover check."""
    state_dict, consumed = _recording(state_dict)

    def _lin(key):
        return {
            "weight": jnp.asarray(_to_np(state_dict[f"{key}.weight"]).T),
            "bias": jnp.asarray(_to_np(state_dict[f"{key}.bias"])),
        }

    def _ln(key):
        return {
            "scale": jnp.asarray(_to_np(state_dict[f"{key}.weight"])),
            "bias": jnp.asarray(_to_np(state_dict[f"{key}.bias"])),
        }

    new = dict(params)
    new["embed"] = _checked(
        "embed",
        {"weight": jnp.asarray(_to_np(state_dict["embed.weight"]))},
        params["embed"],
    )
    new["pos"] = _checked(
        "pos",
        {"weight": jnp.asarray(_to_np(state_dict["pos.weight"]))},
        params["pos"],
    )
    blocks = []
    for i, expect in enumerate(params["blocks"]):
        t = f"blocks.{i}"
        in_proj = _lin(f"{t}.attn.in_proj")
        out_proj = _lin(f"{t}.attn.out_proj")
        fc1, fc2 = _lin(f"{t}.mlp.fc1"), _lin(f"{t}.mlp.fc2")
        block = {
            "ln1": _ln(f"{t}.ln1"),
            "attn": {
                "wqkv": in_proj["weight"],
                "bqkv": in_proj["bias"],
                "wo": out_proj["weight"],
                "bo": out_proj["bias"],
            },
            "ln2": _ln(f"{t}.ln2"),
            "mlp": {
                "w1": fc1["weight"],
                "b1": fc1["bias"],
                "w2": fc2["weight"],
                "b2": fc2["bias"],
            },
        }
        blocks.append(_checked(t, block, expect))
    new["blocks"] = tuple(blocks)
    new["ln_f"] = _checked("ln_f", _ln("ln_f"), params["ln_f"])
    _check_leftover(
        state_dict, consumed,
        f"{len(params['blocks'])}-block TransformerLM",
    )
    return new


_PRETRAINED_LOADERS = {
    "alexnet": load_pretrained_alexnet,
    "resnet18": load_pretrained_resnet18,
    "resnet34": load_pretrained_resnet34,
    "resnet50": load_pretrained_resnet50,
    "resnet101": load_pretrained_resnet101,
    "resnet152": load_pretrained_resnet152,
    "vgg11": _pt(load_pretrained_vgg, "vgg11"),
    "vgg13": _pt(load_pretrained_vgg, "vgg13"),
    "vgg16": _pt(load_pretrained_vgg, "vgg16"),
    "vgg19": _pt(load_pretrained_vgg, "vgg19"),
    # s2d stems share the exact parameter layout, so the same torch
    # checkpoints load into them (the "_s2d = same checkpoints" promise)
    "alexnet_s2d": _pt(load_pretrained_alexnet, space_to_depth=True),
    "resnet18_s2d": _pt(load_pretrained_resnet18, space_to_depth=True),
    "resnet34_s2d": _pt(load_pretrained_resnet34, space_to_depth=True),
    "resnet50_s2d": _pt(load_pretrained_resnet50, space_to_depth=True),
    "resnet101_s2d": _pt(load_pretrained_resnet101, space_to_depth=True),
    "resnet152_s2d": _pt(load_pretrained_resnet152, space_to_depth=True),
}


def pretrained_from_config(training: Mapping[str, object], key=None):
    """Entrypoint-shared ``training.pretrained_path`` handling: validate the
    model name, derive the head-init key from ``training.seed`` when the
    caller has no rank-seeded stream, and load. Returns
    ``(model, params, model_state)``."""
    import jax

    loader = _PRETRAINED_LOADERS.get(str(training["model"]))
    if loader is None:
        raise ValueError(
            "training.pretrained_path supports models "
            f"{sorted(_PRETRAINED_LOADERS)} (got {training['model']!r})"
        )
    if key is None:
        key = jax.random.key(int(training.get("seed") or 0))
    from tpuddp.config import num_classes_from

    return loader(
        str(training["pretrained_path"]),
        key,
        num_classes=num_classes_from(training),
        image_size=int(training.get("image_size") or 224),
    )
