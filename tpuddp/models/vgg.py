"""VGG family (configurations A/B/D/E = VGG-11/13/16/19) — NHWC,
torchvision-layout-compatible.

Extends the zoo beyond the reference's AlexNet (data_and_toy_model.py:41-45)
with the classic torchvision CNNs a tutorial user reaches for. Both the
tpuddp Sequential AND the torchvision ``features.N`` index map are generated
from ONE plan per config, so the checkpoint converter's correspondence holds
by construction (tpuddp.models.torch_import.convert_vgg_state_dict;
logit-exact tests in tests/test_torch_import.py).
"""

from __future__ import annotations

from tpuddp import nn

# torchvision cfgs: numbers are conv widths, "M" is a 2x2/s2 maxpool
VGG_PLANS = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
              512, 512, 512, "M"],
    "vgg19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
              512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


def vgg_conv_indices(name: str):
    """The torchvision ``features.N`` indices that hold convs — identical to
    the conv positions in tpuddp's Sequential, because both are generated
    from the same plan (conv -> +2 for conv+ReLU, "M" -> +1 for the pool)."""
    idx, out = 0, []
    for item in VGG_PLANS[name]:
        if item == "M":
            idx += 1
        else:
            out.append(idx)
            idx += 2
    return tuple(out)


def vgg_classifier_linear_indices(name: str):
    """Sequential indices of the three classifier Linears: features occupy
    [0, F), then AdaptiveAvgPool@F, Flatten@F+1, Linear@F+2, ReLU, Dropout,
    Linear@F+5, ReLU, Dropout, Linear@F+8."""
    f = 0
    for item in VGG_PLANS[name]:
        f += 1 if item == "M" else 2
    return (f + 2, f + 5, f + 8)


def _vgg(name: str, num_classes: int, dropout: float) -> nn.Sequential:
    features = []
    for item in VGG_PLANS[name]:
        if item == "M":
            features.append(nn.MaxPool2d(2, strides=2))
        else:
            features.append(nn.Conv2d(item, kernel_size=3, padding=1))
            features.append(nn.ReLU())
    classifier = [
        nn.AdaptiveAvgPool2d((7, 7)),
        nn.Flatten(),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Dropout(dropout),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Dropout(dropout),
        nn.Linear(num_classes),
    ]
    return nn.Sequential(*features, *classifier)


def VGG11(num_classes: int = 10, dropout: float = 0.5) -> nn.Sequential:
    """torchvision vgg11 ('A'): 8 convs. Input NHWC, any spatial size >= 32."""
    return _vgg("vgg11", num_classes, dropout)


def VGG13(num_classes: int = 10, dropout: float = 0.5) -> nn.Sequential:
    """torchvision vgg13 ('B'): 10 convs."""
    return _vgg("vgg13", num_classes, dropout)


def VGG16(num_classes: int = 10, dropout: float = 0.5) -> nn.Sequential:
    """torchvision vgg16 ('D'): 13 convs."""
    return _vgg("vgg16", num_classes, dropout)


def VGG19(num_classes: int = 10, dropout: float = 0.5) -> nn.Sequential:
    """torchvision vgg19 ('E'): 16 convs."""
    return _vgg("vgg19", num_classes, dropout)
