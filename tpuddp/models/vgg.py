"""VGG-11 (configuration 'A') — NHWC, torchvision-layout-compatible.

Extends the zoo beyond the reference's AlexNet (data_and_toy_model.py:41-45)
with the other classic torchvision CNN a tutorial user reaches for; the layer
ordering matches torchvision's ``vgg11`` exactly, so
``tpuddp.models.torch_import.convert_vgg11_state_dict`` maps a torchvision
checkpoint in logit-exactly (tests/test_torch_import.py).
"""

from __future__ import annotations

from tpuddp import nn


def VGG11(num_classes: int = 10, dropout: float = 0.5) -> nn.Sequential:
    """torchvision VGG-11: 8 conv blocks (3x3/p1, maxpool after widths
    64/128/256x2/512x2/512x2) -> adaptive 7x7 avg pool -> 3-layer classifier.
    Input NHWC, any spatial size >= 32."""
    features = []
    in_plan = [(64, True), (128, True), (256, False), (256, True),
               (512, False), (512, True), (512, False), (512, True)]
    for width, pool in in_plan:
        features.append(nn.Conv2d(width, kernel_size=3, padding=1))
        features.append(nn.ReLU())
        if pool:
            features.append(nn.MaxPool2d(2, strides=2))
    classifier = [
        nn.AdaptiveAvgPool2d((7, 7)),
        nn.Flatten(),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Dropout(dropout),
        nn.Linear(4096),
        nn.ReLU(),
        nn.Dropout(dropout),
        nn.Linear(num_classes),
    ]
    return nn.Sequential(*features, *classifier)
