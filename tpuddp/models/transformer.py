"""Decoder-only transformer family — the token-stream workload (ROADMAP
open item 3) and the proving ground for the future ``("data", "model")``
mesh (open item 1).

Architecture: learned token + position embeddings, pre-LayerNorm blocks of
causal multi-head attention (joined QKV projection) and a GELU MLP, a final
LayerNorm, and an LM head **tied** to the token embedding (logits =
h @ embed.T — no separate head matrix, the GPT-2 convention).

Every parameter carries *logical axis names* following exactly the rule
table of SNIPPETS.md [2] (``heads``/``mlp``/``joined_kv`` -> the "model"
mesh axis; ``batch``/``embed``/``kv``/``seq`` unsharded), exposed through
:func:`param_logical_axes` / :func:`partition_spec` so the family drops into
a 2-D ``("data", "model")`` mesh unchanged once the mesh work lands: the
tensor-parallel split is already declared, only the ``with_sharding_
constraint`` plumbing is missing.

Three entry points share one set of per-block math helpers, so the
full-sequence forward and the serving decode path cannot drift apart:

- ``apply(params, state, tokens, ctx)``    — full causal forward, ``(B, T)``
  int tokens -> ``(B, T, V)`` logits (training / eval / zoo protocol);
- ``prefill(params, kpool, vpool, table_row, tokens, length)`` — one
  prompt's forward at a padded length bucket, committing its K/V into the
  paged pool and returning the last real position's logits (the first
  sampled token — TTFT's clock stops here);
- ``decode_step(params, kpool, vpool, tables, lengths, tokens)`` — the
  fixed-shape ``(max_slots, 1)`` token step: one new token per slot, K/V
  read/written through per-slot block tables (tpuddp/serving/decode/).

Per-slot decode math depends only on that slot's own token, length, block
table, and pool blocks — never on which other sequences share the batch —
which is what makes continuous batching numerically invisible (the
end-to-end acceptance test asserts bitwise-identical tokens vs a
single-sequence decode).
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from tpuddp import nn
from tpuddp.nn.core import Context, Module

# SNIPPETS.md [2]'s DEFAULT_RULES, with its "mp" axis spelled "model" (the
# mesh axis name of ROADMAP open item 1): which mesh axis each LOGICAL
# parameter axis shards over. None = replicated / data-sharded only.
PARTITION_RULES = {
    "batch": None,
    "heads": "model",
    "embed": None,
    "mlp": "model",
    "joined_kv": "model",
    "kv": None,
    "seq": None,
    "vocab": None,
}

_NEG_INF = -1e30  # masked-score fill: finite, so fully-padded rows stay NaN-free


def _uniform(key, shape, fan_in, dtype):
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class TransformerLM(Module):
    """Decoder-only LM. ``num_classes`` aliases ``vocab_size`` so the model
    zoo's ``load_model(name, num_classes=...)`` protocol applies unchanged
    (the label space of a token model IS its vocabulary)."""

    def __init__(
        self,
        num_classes: int = 256,
        d_model: int = 64,
        n_heads: int = 4,
        n_layers: int = 2,
        d_mlp: Optional[int] = None,
        max_seq_len: int = 128,
        dtype=jnp.float32,
    ):
        if d_model % n_heads:
            raise ValueError(
                f"d_model={d_model} not divisible by n_heads={n_heads}"
            )
        self.vocab_size = int(num_classes)
        self.d_model = int(d_model)
        self.n_heads = int(n_heads)
        self.n_layers = int(n_layers)
        self.d_mlp = int(d_mlp) if d_mlp is not None else 4 * self.d_model
        self.max_seq_len = int(max_seq_len)
        self.head_dim = self.d_model // self.n_heads
        self.dtype = dtype
        self._ln = nn.LayerNorm(dtype=dtype)

    # ------------------------------------------------------------------ init --
    def init(self, key, x):
        E, H, Dh, F, V = (
            self.d_model, self.n_heads, self.head_dim, self.d_mlp,
            self.vocab_size,
        )
        k_embed, k_pos, k_blocks = jax.random.split(key, 3)
        ln = {
            "scale": jnp.ones((E,), self.dtype),
            "bias": jnp.zeros((E,), self.dtype),
        }
        blocks = []
        for i in range(self.n_layers):
            kq, ko, k1, k2 = jax.random.split(jax.random.fold_in(k_blocks, i), 4)
            blocks.append({
                "ln1": dict(ln),
                "attn": {
                    "wqkv": _uniform(kq, (E, 3 * H * Dh), E, self.dtype),
                    "bqkv": jnp.zeros((3 * H * Dh,), self.dtype),
                    "wo": _uniform(ko, (H * Dh, E), H * Dh, self.dtype),
                    "bo": jnp.zeros((E,), self.dtype),
                },
                "ln2": dict(ln),
                "mlp": {
                    "w1": _uniform(k1, (E, F), E, self.dtype),
                    "b1": jnp.zeros((F,), self.dtype),
                    "w2": _uniform(k2, (F, E), F, self.dtype),
                    "b2": jnp.zeros((E,), self.dtype),
                },
            })
        params = {
            # N(0, 0.02): the GPT-2 embedding scale — fan-in uniform would
            # start the tied head's logits far too hot at vocab scale
            "embed": {
                "weight": 0.02 * jax.random.normal(
                    k_embed, (V, E), self.dtype
                )
            },
            "pos": {
                "weight": 0.02 * jax.random.normal(
                    k_pos, (self.max_seq_len, E), self.dtype
                )
            },
            "blocks": tuple(blocks),
            "ln_f": dict(ln),
        }
        return params, ()

    def divergent_state(self) -> bool:
        return False  # parameters only, no buffers

    # ----------------------------------------------------------- block math --
    def _norm(self, p, x):
        y, _ = self._ln.apply(p, (), x, Context(train=False))
        return y

    def _qkv(self, p, a):
        """``a (..., E) -> q, k, v (..., H, Dh)`` through the joined
        projection (the ``joined_kv`` logical axis)."""
        qkv = a @ p["wqkv"] + p["bqkv"]
        qkv = qkv.reshape(a.shape[:-1] + (3, self.n_heads, self.head_dim))
        return qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]

    def _attn_out(self, p, o):
        """``o (..., H, Dh) -> (..., E)`` through the output projection."""
        return o.reshape(o.shape[:-2] + (-1,)) @ p["wo"] + p["bo"]

    def _mlp(self, p, a):
        # exact (erf) GELU — torch nn.GELU's default, so imported torch
        # checkpoints reproduce logits without an activation mismatch
        return jax.nn.gelu(a @ p["w1"] + p["b1"], approximate=False) @ p["w2"] + p["b2"]

    def _block_full(self, p, h, mask):
        """One pre-LN block over a full ``(B, T, E)`` sequence; returns the
        new hidden plus this layer's K/V ``(B, T, H, Dh)`` (the prefill
        path's cache feed)."""
        a = self._norm(p["ln1"], h)
        q, k, v = self._qkv(p["attn"], a)
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(self.head_dim)
        scores = jnp.where(mask[None, None, :, :], scores, _NEG_INF)
        attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(h.dtype)
        h = h + self._attn_out(p["attn"], jnp.einsum("bhqk,bkhd->bqhd", attn, v))
        return h + self._mlp(p["mlp"], self._norm(p["ln2"], h)), (k, v)

    # ---------------------------------------------------------- full forward --
    def apply(self, params, state, x, ctx: Context):
        tokens = jnp.asarray(x).astype(jnp.int32)
        B, T = tokens.shape
        if T > self.max_seq_len:
            raise ValueError(
                f"sequence length {T} exceeds max_seq_len={self.max_seq_len}"
            )
        h = (
            jnp.take(params["embed"]["weight"], tokens, axis=0)
            + params["pos"]["weight"][:T]
        )
        mask = jnp.tril(jnp.ones((T, T), bool))
        for p in params["blocks"]:
            h, _ = self._block_full(p, h, mask)
        h = self._norm(params["ln_f"], h)
        return h @ params["embed"]["weight"].T, state

    # -------------------------------------------------------------- serving --
    def prefill(self, params, kpool, vpool, table_row, tokens, length):
        """One prompt's bucketed forward + paged-pool commit.

        ``tokens``: ``(1, P)`` int32, the prompt zero-padded to bucket ``P``;
        ``length``: the true prompt length (static-shape-safe scalar);
        ``table_row``: ``(max_blocks,)`` int32 pool-block ids for this
        sequence (0 = the reserved garbage block). Positions ``p < length``
        scatter their K/V to ``(table_row[p // BS], p % BS)``; pad positions
        are redirected into garbage block 0, so the pool write is one
        fixed-shape scatter per layer. Returns ``(last_logits (V,), kpool,
        vpool)`` — the logits of position ``length - 1``, i.e. the
        distribution of the first generated token."""
        P = tokens.shape[1]
        BS = kpool.shape[2]
        pos = jnp.arange(P)
        live = pos < length
        dest_blk = jnp.where(live, jnp.take(table_row, pos // BS), 0)
        dest_off = pos % BS
        h = (
            jnp.take(params["embed"]["weight"], tokens.astype(jnp.int32), axis=0)
            + params["pos"]["weight"][:P]
        )
        mask = jnp.tril(jnp.ones((P, P), bool))
        for li, p in enumerate(params["blocks"]):
            h, (k, v) = self._block_full(p, h, mask)
            kpool = kpool.at[li, dest_blk, dest_off].set(k[0])
            vpool = vpool.at[li, dest_blk, dest_off].set(v[0])
        h_last = jnp.take(h[0], length - 1, axis=0)
        h_last = self._norm(params["ln_f"], h_last)
        return h_last @ params["embed"]["weight"].T, kpool, vpool

    def decode_step(self, params, kpool, vpool, tables, lengths, tokens):
        """The fixed-shape ``(max_slots, 1)`` token step.

        ``tokens (S,)``: each slot's last sampled token; ``lengths (S,)``:
        tokens already committed per slot (= the new token's position);
        ``tables (S, MB)``: per-slot block tables. Every slot writes its new
        K/V at ``(table[length // BS], length % BS)`` (inactive slots carry
        all-zero tables and length 0, so their writes land in garbage block
        0), attends over positions ``0..length`` inclusive, and returns its
        next-token logits. One compiled program regardless of which
        sequences occupy which slots."""
        S, MB = tables.shape
        BS = kpool.shape[2]
        ctx_pos = jnp.arange(MB * BS)
        x = (
            jnp.take(params["embed"]["weight"], tokens.astype(jnp.int32), axis=0)
            + jnp.take(params["pos"]["weight"], lengths, axis=0)
        )
        blk = jnp.take_along_axis(tables, (lengths // BS)[:, None], axis=1)[:, 0]
        off = lengths % BS
        mask = ctx_pos[None, :] <= lengths[:, None]  # (S, MB*BS)
        for li, p in enumerate(params["blocks"]):
            a = self._norm(p["ln1"], x)
            q, k, v = self._qkv(p["attn"], a)  # (S, H, Dh)
            kpool = kpool.at[li, blk, off].set(k)
            vpool = vpool.at[li, blk, off].set(v)
            # gather each slot's context through its block table; positions
            # past the slot's length read stale/garbage blocks and are masked
            kctx = jnp.take(kpool[li], tables, axis=0).reshape(
                S, MB * BS, self.n_heads, self.head_dim
            )
            vctx = jnp.take(vpool[li], tables, axis=0).reshape(
                S, MB * BS, self.n_heads, self.head_dim
            )
            scores = jnp.einsum("shd,skhd->shk", q, kctx) / math.sqrt(self.head_dim)
            scores = jnp.where(mask[:, None, :], scores, _NEG_INF)
            attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
            x = x + self._attn_out(p["attn"], jnp.einsum("shk,skhd->shd", attn, vctx))
            x = x + self._mlp(p["mlp"], self._norm(p["ln2"], x))
        x = self._norm(params["ln_f"], x)
        return x @ params["embed"]["weight"].T, kpool, vpool


# ----------------------------------------------------- partition metadata --


def param_logical_axes(model: TransformerLM, params) -> dict:
    """A pytree congruent with ``params`` whose leaves are tuples of LOGICAL
    axis names (the vocabulary of :data:`PARTITION_RULES` / snippet [2])."""
    ln = {"scale": ("embed",), "bias": ("embed",)}
    block = {
        "ln1": dict(ln),
        "attn": {
            "wqkv": ("embed", "joined_kv"),
            "bqkv": ("joined_kv",),
            "wo": ("heads", "embed"),
            "bo": ("embed",),
        },
        "ln2": dict(ln),
        "mlp": {
            "w1": ("embed", "mlp"),
            "b1": ("mlp",),
            "w2": ("mlp", "embed"),
            "b2": ("embed",),
        },
    }
    return {
        "embed": {"weight": ("vocab", "embed")},
        "pos": {"weight": ("seq", "embed")},
        "blocks": tuple(dict(block) for _ in params["blocks"]),
        "ln_f": dict(ln),
    }


def partition_spec(model: TransformerLM, params, rules=None) -> dict:
    """Map every parameter's logical axes through the rule table to MESH axis
    names: the pytree a 2-D ``("data", "model")`` mesh feeds straight into
    ``NamedSharding``/``with_sharding_constraint`` — e.g. ``wqkv`` ->
    ``(None, "model")`` (column-split joined QKV), ``w2`` -> ``("model",
    None)`` (row-split MLP contraction)."""
    rules = dict(PARTITION_RULES if rules is None else rules)
    axes = param_logical_axes(model, params)
    return jax.tree_util.tree_map(
        lambda names: tuple(rules[n] for n in names),
        axes,
        is_leaf=lambda leaf: isinstance(leaf, tuple)
        and all(isinstance(n, str) for n in leaf),
    )


def prefill_buckets(max_prompt_len: int):
    """The power-of-two prompt-length ladder: at most ``log2(max) + 1``
    compiled prefill programs (the serving scheduler's bucket invariant,
    tpuddp/utils/batching.bucket_sizes, applied to the sequence axis)."""
    from tpuddp.utils import batching

    return batching.bucket_sizes(max_prompt_len)
