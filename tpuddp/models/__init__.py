"""Model zoo. The reference's zoo is ``load_model`` = pretrained AlexNet with
its classifier head swapped for CIFAR-10 (data_and_toy_model.py:41-45); tpuddp
adds genuinely small toy models for fast CI (per SURVEY.md scale calibration),
ResNet-18/34 (BasicBlock) + ResNet-50/101/152 (Bottleneck), VGG-11/13/16/19, and
CIFAR-stem/space-to-depth variants; all torch-importable."""

from tpuddp.models.toy import ToyCNN, ToyMLP  # noqa: F401
from tpuddp.models.alexnet import AlexNet  # noqa: F401
from tpuddp.models.transformer import TransformerLM  # noqa: F401
from tpuddp.models.resnet import (  # noqa: F401
    ResNet18, ResNet34, ResNet50, ResNet101, ResNet152,
)
from tpuddp.models.vgg import VGG11, VGG13, VGG16, VGG19  # noqa: F401

from functools import partial as _partial

_REGISTRY = {
    "toy_mlp": ToyMLP,
    "toy_cnn": ToyCNN,
    "alexnet": AlexNet,
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
    "vgg11": VGG11,
    "vgg13": VGG13,
    "vgg16": VGG16,
    "vgg19": VGG19,
    # CIFAR-style stem (3x3 conv, no maxpool) for small native resolutions
    "resnet18_small": _partial(ResNet18, small_input=True),
    "resnet34_small": _partial(ResNet34, small_input=True),
    "resnet50_small": _partial(ResNet50, small_input=True),
    "resnet101_small": _partial(ResNet101, small_input=True),
    "resnet152_small": _partial(ResNet152, small_input=True),
    # decoder-only transformer family (tpuddp/models/transformer.py):
    # num_classes aliases vocab_size; partition rules follow SNIPPETS.md
    # [2]'s table so these drop into the future ("data","model") mesh
    "transformer_tiny": _partial(
        TransformerLM, d_model=64, n_heads=4, n_layers=2, max_seq_len=128,
    ),
    "transformer_small": _partial(
        TransformerLM, d_model=128, n_heads=8, n_layers=4, max_seq_len=256,
    ),
    # exact space-to-depth stem reparameterization (same params/checkpoints;
    # faster MXU mapping for the thin-channel strided stems)
    "alexnet_s2d": _partial(AlexNet, space_to_depth=True),
    "resnet18_s2d": _partial(ResNet18, space_to_depth=True),
    "resnet34_s2d": _partial(ResNet34, space_to_depth=True),
    "resnet50_s2d": _partial(ResNet50, space_to_depth=True),
    "resnet101_s2d": _partial(ResNet101, space_to_depth=True),
    "resnet152_s2d": _partial(ResNet152, space_to_depth=True),
}


def load_model(name: str = "alexnet", num_classes: int = 10, **kwargs):
    """Registry-based analog of the reference's ``load_model()``."""
    try:
        cls = _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown model {name!r}; one of {sorted(_REGISTRY)}")
    return cls(num_classes=num_classes, **kwargs)


__all__ = [
    "ToyMLP", "ToyCNN", "AlexNet", "ResNet18", "ResNet34", "ResNet50",
    "ResNet101", "ResNet152",
    "TransformerLM",
    "VGG11", "VGG13", "VGG16", "VGG19",
    "load_model",
]
