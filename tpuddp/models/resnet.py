"""ResNets (NHWC) — ResNet-18/34 (BasicBlock; -18 is the multi-host CIFAR
BASELINE config, BASELINE.json configs[4]) and ResNet-50/101/152 (Bottleneck).
BatchNorm layers honor convert_sync_batchnorm / ``sync_bn=True`` so
cross-replica statistic sync works under DP."""

from __future__ import annotations

import jax

from tpuddp import nn
from tpuddp.nn.core import Context, Module


class BasicBlock(Module):
    """Two 3x3 convs with identity (or 1x1-projected) shortcut."""

    def __init__(self, features: int, stride: int = 1, sync_bn: bool = False):
        self.features = features
        self.stride = stride
        self.conv1 = nn.Conv2d(features, 3, strides=stride, padding=1, use_bias=False)
        self.bn1 = nn.BatchNorm(sync=sync_bn)
        self.conv2 = nn.Conv2d(features, 3, padding=1, use_bias=False)
        self.bn2 = nn.BatchNorm(sync=sync_bn)
        self.down_conv = nn.Conv2d(features, 1, strides=stride, use_bias=False)
        self.down_bn = nn.BatchNorm(sync=sync_bn)

    def children(self):
        return (self.conv1, self.bn1, self.conv2, self.bn2, self.down_conv, self.down_bn)

    def divergent_state(self) -> bool:
        return False  # aggregates child state only; owns no buffers of its own

    def init(self, key, x):
        keys = jax.random.split(key, 6)
        in_ch = x.shape[-1]
        p, s = {}, {}
        p["conv1"], _, h = self.conv1.init_with_output_shape(keys[0], x)
        p["bn1"], s["bn1"], h = self.bn1.init_with_output_shape(keys[1], h)
        p["conv2"], _, h = self.conv2.init_with_output_shape(keys[2], h)
        p["bn2"], s["bn2"], _ = self.bn2.init_with_output_shape(keys[3], h)
        if self.stride != 1 or in_ch != self.features:
            p["down_conv"], _, d = self.down_conv.init_with_output_shape(keys[4], x)
            p["down_bn"], s["down_bn"], _ = self.down_bn.init_with_output_shape(keys[5], d)
        return p, s

    def apply(self, params, state, x, ctx: Context):
        new_state = dict(state)
        h, _ = self.conv1.apply(params["conv1"], (), x, ctx)
        h, new_state["bn1"] = self.bn1.apply(params["bn1"], state["bn1"], h, ctx)
        h, _ = nn.ReLU().apply((), (), h, ctx)
        h, _ = self.conv2.apply(params["conv2"], (), h, ctx)
        h, new_state["bn2"] = self.bn2.apply(params["bn2"], state["bn2"], h, ctx)
        if "down_conv" in params:
            sc, _ = self.down_conv.apply(params["down_conv"], (), x, ctx)
            sc, new_state["down_bn"] = self.down_bn.apply(
                params["down_bn"], state["down_bn"], sc, ctx
            )
        else:
            sc = x
        return jax.nn.relu(h + sc), new_state


class Bottleneck(Module):
    """1x1 reduce -> 3x3 (strided, torchvision v1.5 placement) -> 1x1 expand
    (x4), with identity (or 1x1-projected) shortcut — the ResNet-50/101/152
    block (torchvision-layout state_dict keys: conv1/bn1, conv2/bn2,
    conv3/bn3, downsample.{0,1})."""

    expansion = 4

    def __init__(self, features: int, stride: int = 1, sync_bn: bool = False):
        self.features = features  # the bottleneck width; output is 4x
        self.stride = stride
        self.conv1 = nn.Conv2d(features, 1, use_bias=False)
        self.bn1 = nn.BatchNorm(sync=sync_bn)
        self.conv2 = nn.Conv2d(features, 3, strides=stride, padding=1, use_bias=False)
        self.bn2 = nn.BatchNorm(sync=sync_bn)
        self.conv3 = nn.Conv2d(features * self.expansion, 1, use_bias=False)
        self.bn3 = nn.BatchNorm(sync=sync_bn)
        self.down_conv = nn.Conv2d(
            features * self.expansion, 1, strides=stride, use_bias=False
        )
        self.down_bn = nn.BatchNorm(sync=sync_bn)

    def children(self):
        return (
            self.conv1, self.bn1, self.conv2, self.bn2, self.conv3, self.bn3,
            self.down_conv, self.down_bn,
        )

    def divergent_state(self) -> bool:
        return False  # aggregates child state only; owns no buffers of its own

    def init(self, key, x):
        keys = jax.random.split(key, 8)
        in_ch = x.shape[-1]
        p, s = {}, {}
        p["conv1"], _, h = self.conv1.init_with_output_shape(keys[0], x)
        p["bn1"], s["bn1"], h = self.bn1.init_with_output_shape(keys[1], h)
        p["conv2"], _, h = self.conv2.init_with_output_shape(keys[2], h)
        p["bn2"], s["bn2"], h = self.bn2.init_with_output_shape(keys[3], h)
        p["conv3"], _, h = self.conv3.init_with_output_shape(keys[4], h)
        p["bn3"], s["bn3"], _ = self.bn3.init_with_output_shape(keys[5], h)
        if self.stride != 1 or in_ch != self.features * self.expansion:
            p["down_conv"], _, d = self.down_conv.init_with_output_shape(keys[6], x)
            p["down_bn"], s["down_bn"], _ = self.down_bn.init_with_output_shape(keys[7], d)
        return p, s

    def apply(self, params, state, x, ctx: Context):
        new_state = dict(state)
        h, _ = self.conv1.apply(params["conv1"], (), x, ctx)
        h, new_state["bn1"] = self.bn1.apply(params["bn1"], state["bn1"], h, ctx)
        h = jax.nn.relu(h)
        h, _ = self.conv2.apply(params["conv2"], (), h, ctx)
        h, new_state["bn2"] = self.bn2.apply(params["bn2"], state["bn2"], h, ctx)
        h = jax.nn.relu(h)
        h, _ = self.conv3.apply(params["conv3"], (), h, ctx)
        h, new_state["bn3"] = self.bn3.apply(params["bn3"], state["bn3"], h, ctx)
        if "down_conv" in params:
            sc, _ = self.down_conv.apply(params["down_conv"], (), x, ctx)
            sc, new_state["down_bn"] = self.down_bn.apply(
                params["down_bn"], state["down_bn"], sc, ctx
            )
        else:
            sc = x
        return jax.nn.relu(h + sc), new_state


class GlobalAvgPool(Module):
    def apply(self, params, state, x, ctx: Context):
        return x.mean(axis=(1, 2)), state


def _resnet(
    depths,
    num_classes: int,
    sync_bn: bool,
    small_input: bool,
    space_to_depth: bool = False,
    block=BasicBlock,
) -> nn.Sequential:
    """stem + ``block`` stages at widths [64,128,256,512] + GAP head.
    ``small_input=True`` uses the CIFAR stem (3x3/1 conv, no maxpool) for
    native 32x32 training — the TPU-friendly alternative to the reference's
    resize-everything-to-224. ``space_to_depth=True`` swaps the full stem's
    7x7/s2 3-channel conv for its exact space-to-depth reparameterization
    (same parameters/checkpoints; see nn.SpaceToDepthConv2d). ``block`` is
    BasicBlock (ResNet-18/34) or Bottleneck (ResNet-50)."""
    if small_input:
        if space_to_depth:
            raise ValueError(
                "space_to_depth applies to the full 7x7/s2 stem; the "
                "small_input CIFAR stem (3x3/s1) has no stride to block"
            )
        stem = [
            nn.Conv2d(64, 3, strides=1, padding=1, use_bias=False),
            nn.BatchNorm(sync=sync_bn),
            nn.ReLU(),
        ]
    else:
        stem_cls = nn.SpaceToDepthConv2d if space_to_depth else nn.Conv2d
        stem = [
            stem_cls(64, 7, strides=2, padding=3, use_bias=False),
            nn.BatchNorm(sync=sync_bn),
            nn.ReLU(),
            nn.MaxPool2d(3, strides=2, padding=1),
        ]
    blocks = []
    for n_blocks, (width, stride) in zip(
        depths, [(64, 1), (128, 2), (256, 2), (512, 2)]
    ):
        blocks.append(block(width, stride=stride, sync_bn=sync_bn))
        blocks.extend(
            block(width, stride=1, sync_bn=sync_bn)
            for _ in range(n_blocks - 1)
        )
    head = [GlobalAvgPool(), nn.Linear(num_classes)]
    return nn.Sequential(*stem, *blocks, *head)


def ResNet18(
    num_classes: int = 10, sync_bn: bool = False, small_input: bool = False,
    space_to_depth: bool = False,
) -> nn.Sequential:
    """Standard ResNet-18: [2,2,2,2] BasicBlocks."""
    return _resnet((2, 2, 2, 2), num_classes, sync_bn, small_input, space_to_depth)


def ResNet34(
    num_classes: int = 10, sync_bn: bool = False, small_input: bool = False,
    space_to_depth: bool = False,
) -> nn.Sequential:
    """Standard ResNet-34: [3,4,6,3] BasicBlocks."""
    return _resnet((3, 4, 6, 3), num_classes, sync_bn, small_input, space_to_depth)


def ResNet50(
    num_classes: int = 10, sync_bn: bool = False, small_input: bool = False,
    space_to_depth: bool = False,
) -> nn.Sequential:
    """Standard ResNet-50: [3,4,6,3] Bottleneck blocks (torchvision v1.5
    stride placement: the 3x3 conv strides)."""
    return _resnet(
        (3, 4, 6, 3), num_classes, sync_bn, small_input, space_to_depth,
        block=Bottleneck,
    )


def ResNet101(
    num_classes: int = 10, sync_bn: bool = False, small_input: bool = False,
    space_to_depth: bool = False,
) -> nn.Sequential:
    """Standard ResNet-101: [3,4,23,3] Bottleneck blocks."""
    return _resnet(
        (3, 4, 23, 3), num_classes, sync_bn, small_input, space_to_depth,
        block=Bottleneck,
    )


def ResNet152(
    num_classes: int = 10, sync_bn: bool = False, small_input: bool = False,
    space_to_depth: bool = False,
) -> nn.Sequential:
    """Standard ResNet-152: [3,8,36,3] Bottleneck blocks."""
    return _resnet(
        (3, 8, 36, 3), num_classes, sync_bn, small_input, space_to_depth,
        block=Bottleneck,
    )
