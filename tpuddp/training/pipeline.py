"""Async pipelined runner — keep the device busy while the host stages.

BASELINE.md's dispatch-RTT section shows the same 6.06 ms/step device program
costing 12-40 ms/step wall: every dispatch pays host batch assembly, staging,
and tunnel RTT *serially* unless they are overlapped. Scan fusion amortizes
the per-dispatch cost but cannot hide the host work between dispatches. This
module owns the overlap:

- **host staging pipeline**: the pass stages up to ``depth`` device chunks
  ahead of the dispatch cursor (``jax.device_put``/sharded placement is
  async, so chunk N+1's host->HBM transfer rides the runtime's stream while
  chunk N's compute runs). The staged queue is byte-capped against the shared
  ~256 MB staging budget (``tpuddp/utils/batching.py``) — depth x chunk bytes
  is real HBM.
- **dispatch pipelining**: dispatch N+1 is enqueued before N's results land
  (JAX dispatch is asynchronous; the state dependency chains on device), and
  per-dispatch metric pytrees are harvested by a *deferred readback drain* —
  accumulated device-side in dispatch order, fetched only at the telemetry
  window fence / epoch boundary. No per-dispatch ``block_until_ready``,
  ever, unless ``sync_readback`` explicitly asks for the serial cadence
  (the A/B baseline ``bench.py --pipeline`` measures against).
- **occupancy accounting**: the pass reports, per dispatch, the time it spent
  blocked acquiring host batches (``host_stall``), the staged-chunk queue
  depth, and the number of issued-but-unobserved dispatches (in-flight
  depth) through the telemetry hooks -> ``step_stats`` windows
  (schema v3 fields), so wall/device -> 1.0 is directly observable.

Correctness contract: the pipeline NEVER touches the compiled step program
(HLO is byte-identical pipeline-on/off) and never reorders dispatches, so a
pipelined run is bitwise-identical to the synchronous path on params,
opt-state, and comm_state at every depth — asserted in
``tests/test_pipeline.py`` and the full gate's pipeline leg. A preemption
drain returns the state as of the last *issued* dispatch; the emergency
checkpoint's device fetch flushes every in-flight dispatch before anything is
written, so no batch is lost or double-applied.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import asdict, dataclass

import jax

from tpuddp.observability import telemetry as telemetry_lib
from tpuddp.observability import trace as trace_lib
from tpuddp.training.step import accumulate_metrics, stack_batches
from tpuddp.utils import batching

# The training.pipeline config block (unknown keys refused — the
# training-block contract, tpuddp/config.py::_merge_refusing_unknown).
PIPELINE_DEFAULTS = {
    "depth": 2,  # staged device chunks held ahead of the dispatch cursor
    # (byte-capped by the ~256 MB staging budget; 1 = single-chunk lookahead)
    "host_workers": 2,  # PrefetchLoader worker threads assembling host
    # batches (0 = inline loading on the dispatch thread)
    "device_augment": True,  # fold normalize/flip/resize into the compiled
    # step (managed path; the native step always compiles augment in) so host
    # workers only decode and stack
    "sync_readback": False,  # serial cadence: block on every dispatch's
    # results before issuing the next (the pre-pipeline A/B baseline; bitwise
    # identical, strictly slower)
}


@dataclass(frozen=True)
class PipelineConfig:
    depth: int = 2
    host_workers: int = 2
    device_augment: bool = True
    sync_readback: bool = False

    def as_dict(self) -> dict:
        return asdict(self)


DEFAULT = PipelineConfig()
# ``pipeline: false`` — the synchronous A/B reference: no staged lookahead,
# no loader workers, one blocking readback per dispatch. device_augment stays
# at its default on purpose: augment placement changes the compiled program,
# and the on/off pair must stay HLO- and bitwise-identical.
SYNCHRONOUS = PipelineConfig(depth=1, host_workers=0, sync_readback=True)


def resolve_pipeline(block) -> PipelineConfig:
    """Resolve the ``training.pipeline`` knob: None/True -> defaults, False ->
    the synchronous reference mode, a dict -> defaults overridden with
    unknown-key refusal (a typo'd knob must not silently run a different
    pipeline than the file says)."""
    if isinstance(block, PipelineConfig):
        return block
    if block is None or block is True:
        return DEFAULT
    if block is False:
        return SYNCHRONOUS
    if not isinstance(block, dict):
        raise ValueError(
            f"training.pipeline must be true/false or a mapping, got {block!r}"
        )
    from tpuddp.config import _merge_refusing_unknown

    cfg = _merge_refusing_unknown(PIPELINE_DEFAULTS, block, "training.pipeline")
    depth = int(cfg["depth"])
    if depth < 1:
        raise ValueError(f"training.pipeline.depth must be >= 1, got {depth}")
    workers = int(cfg["host_workers"])
    if workers < 0:
        raise ValueError(
            f"training.pipeline.host_workers must be >= 0, got {workers}"
        )
    return PipelineConfig(
        depth=depth,
        host_workers=workers,
        device_augment=bool(cfg["device_augment"]),
        sync_readback=bool(cfg["sync_readback"]),
    )


def staging_depth_for(depth: int, chunk_nbytes) -> int:
    """Byte-cap the staged-chunk queue: ``depth`` chunks, bounded so
    depth x chunk bytes stays inside the shared staging budget (the queue is
    real HBM; one policy with every other device-queue cap —
    ``batching.resolve_fuse``). Unknown chunk bytes keep the configured
    depth — the chunker upstream already bounded one chunk by the same
    budget."""
    return batching.resolve_fuse(chunk_nbytes, cap=max(1, int(depth)))


def _leaf_ready(metrics) -> bool:
    """Best-effort 'has this dispatch completed?' probe: True when the first
    array leaf reports ready. Arrays without the probe count as complete —
    the drain then folds eagerly, which is always correct (folding is
    device-side, order-preserving, and never a host sync)."""
    for leaf in jax.tree_util.tree_leaves(metrics):
        ready = getattr(leaf, "is_ready", None)
        if ready is not None:
            try:
                return bool(ready())
            except Exception:
                return True
        return True
    return True


class _ReadbackDrain:
    """Deferred metric harvest: per-dispatch metric pytrees fold into the
    running accumulator in dispatch order (device-side tree adds — async, no
    fetch). The fold is deferred while the dispatch is observably in flight,
    which is what makes the in-flight depth an honest, measurable number;
    the actual host readback happens only at the window fence / epoch end."""

    def __init__(self):
        self.acc = None
        self._pending = deque()

    def offer(self, metrics):
        self._pending.append(metrics)
        # fold every entry whose dispatch has completed (cheap host probe);
        # entries still in flight stay queued — their fold costs nothing to
        # delay, and len(pending) is the in-flight depth telemetry reports
        while self._pending and _leaf_ready(self._pending[0]):
            self.acc = accumulate_metrics(self.acc, self._pending.popleft())

    @property
    def inflight(self) -> int:
        return len(self._pending)

    def drain(self):
        """Fold everything (end of pass / early return). Still no host sync —
        the caller's metric fetch or checkpoint is the fence."""
        while self._pending:
            self.acc = accumulate_metrics(self.acc, self._pending.popleft())
        return self.acc


class StallClock:
    """Accumulates time the dispatch loop spends blocked acquiring host
    batches. With loader workers this is true starvation (the queue was
    empty); with inline loading it is the host batch-assembly time the
    pipeline exists to overlap — either way it is the host-side bound on
    wall/device."""

    def __init__(self):
        self.total = 0.0
        self._since_dispatch = 0.0

    def add(self, dt: float) -> None:
        self.total += dt
        self._since_dispatch += dt

    def take(self) -> float:
        dt, self._since_dispatch = self._since_dispatch, 0.0
        return dt


def stalled_iter(loader, stall: StallClock):
    it = iter(loader)
    while True:
        t0 = time.perf_counter()
        try:
            batch = next(it)
        except StopIteration:
            return
        stall.add(time.perf_counter() - t0)
        yield batch


def _pad_to_cycles(chunk, accum: int):
    """Pad a ragged tail chunk with all-padding (weight-0) micro-batches to a
    whole number of accumulation cycles. Padding batches carry zero sample
    weight, so they contribute nothing to gradients, metrics, or BatchNorm
    statistics (nn/loss.py, nn/norm.py) — the cycle's update averages over
    the live samples only. Cost: up to ``accum - 1`` wasted tail micro-steps
    per epoch, the price of keeping the scan shape static."""
    import numpy as np

    x0, y0, w0 = chunk[-1]
    pad = (-len(chunk)) % accum
    return chunk + [(x0, y0, np.zeros_like(w0))] * pad


def _never():
    return False


def run_pass(
    ddp, state, loader, scan_k: int, step_one, step_many, *,
    cfg: PipelineConfig = DEFAULT, probe_cb=None, accum: int = 1,
    poll=_never, inject_cb=None, tel=None, tracer=None, trace_parent=None,
    comm_attrs=None, snap_cb=None, init_acc=None,
):
    """One pipelined pass over ``loader``: K-fused dispatch with a
    ``cfg.depth``-chunk staged device queue and a deferred readback drain.
    Shared by the train and eval passes; ``step_*(state, batch) ->
    (state, metrics)``.

    Semantics are the synchronous pass's, exactly: same batches, same order,
    same dispatch granularity (``scan_k``-chunks, a padded tail under
    ``accum > 1``, single steps for the remainder), so the result is bitwise
    identical at every depth. ``poll`` (the preemption flag) is checked at
    every batch boundary; an interrupted pass returns early with the state as
    of the last issued dispatch — staged-but-undispatched chunks are dropped
    (the redone epoch re-derives them), and the emergency checkpoint's device
    fetch flushes the in-flight dispatches before anything is written.
    ``inject_cb`` (the ``nan@step=N`` chaos hook) may rewrite each host batch
    before staging. ``tel`` (a :class:`~tpuddp.observability.RunTelemetry`;
    None -> inert) brackets each dispatch and receives the occupancy fields
    (host stall, staged queue depth, in-flight depth).

    Tracing (``tracer``, an :mod:`~tpuddp.observability.trace` Tracer; None
    -> inert): each staged placement lands a ``stage`` span, each jitted
    call a ``dispatch`` span (issue-time window — dispatch is async, so the
    span measures what the HOST paid, matching the recorder's lap
    semantics), the deferred metric drain a ``readback`` span, and — when
    ``comm_attrs`` names a live comm hook — a zero-length ``collective``
    annotation span per dispatch carrying the wire-byte accounting. All
    children of ``trace_parent`` (the driver's epoch span). Pure host
    bracketing of calls this pass already makes: no new fences, bitwise
    identity untouched.

    Step snapshots (``snap_cb``, the async checkpoint engine's hook): called
    between dispatches — AFTER dispatch N's telemetry posts and BEFORE
    dispatch N+1 is issued — with ``(state, real_batches_dispatched,
    drain)``. "Real" excludes the all-padding micro-batches a ragged tail
    stages, so the count addresses actual loader positions. The hook is
    host-side bookkeeping plus async device copies: it must never block
    (the engine skips when its writer queue is full), so the staged queue
    never drains and bitwise identity/HLO are untouched. ``init_acc`` seeds
    the readback drain's accumulator — a resumed mid-epoch pass passes the
    cursor's partial fold so the epoch total equals an uninterrupted run's,
    bitwise.

    Returns ``(state, accumulated_metrics, interrupted)``.
    """
    if tel is None:
        tel = telemetry_lib.NULL
    if tracer is None:
        tracer = trace_lib.NULL
    depth = staging_depth_for(
        cfg.depth,
        (getattr(loader, "batch_nbytes", None) or 0) * max(1, scan_k) or None,
    )
    drain = _ReadbackDrain()
    if init_acc is not None:
        drain.acc = init_acc
    stall = StallClock()
    staged = deque()  # (staged_chunk, n_steps, n_real, n_samples, use_many)
    dispatched_real = 0  # real (non-padding) micro-batches dispatched so far

    def dispatch_oldest():
        nonlocal state, dispatched_real
        chunk, n_steps, n_real, n_samples, use_many = staged.popleft()
        tel.pre_dispatch(n_steps)
        dsp = tracer.start_span(
            "dispatch", trace_lib.KIND_DISPATCH, parent=trace_parent,
            attrs={"steps": n_steps, "samples": n_samples},
        )
        if use_many:
            state, metrics = step_many(state, chunk)
        else:
            state, metrics = step_one(state, chunk)
        if cfg.sync_readback:
            # the serial A/B cadence: results land before the next dispatch
            rsp = tracer.start_span(
                "readback", trace_lib.KIND_READBACK, parent=dsp,
            )
            jax.block_until_ready(metrics)
            tracer.end_span(rsp, sync=True)
        drain.offer(metrics)
        if comm_attrs is not None:
            # the comm hook's bucketed exchange runs INSIDE the compiled
            # program — the host cannot time it, so this is an annotation
            # span (zero-length, nested in the dispatch): which hook, how
            # many wire bytes per optimizer update, how many updates this
            # dispatch carried
            updates = max(1, n_steps // max(1, accum))
            segs = comm_attrs.get("segments")
            if segs:
                # segmented overlap: one collective span per backward
                # segment so trace_breakdown shows K interleaved issues
                # instead of one trailing block
                shared = {
                    k: v for k, v in comm_attrs.items() if k != "segments"
                }
                for seg in segs:
                    tracer.end_span(tracer.start_span(
                        f"grad_comm.seg{seg['segment']}",
                        trace_lib.KIND_COLLECTIVE, parent=dsp,
                        attrs={**shared, **seg, "updates": updates},
                    ))
            else:
                tracer.end_span(tracer.start_span(
                    "grad_comm", trace_lib.KIND_COLLECTIVE, parent=dsp,
                    attrs={**comm_attrs, "updates": updates},
                ))
        tracer.end_span(dsp, inflight=drain.inflight)
        tel.post_dispatch(
            n_steps, n_samples, metrics,
            host_stall_s=stall.take(),
            staging_depth=len(staged),
            inflight_depth=drain.inflight,
        )
        dispatched_real += n_real
        if snap_cb is not None:
            # step-boundary snapshot hook: after this dispatch's telemetry,
            # before the next dispatch — never blocking (see docstring)
            snap_cb(state, dispatched_real, drain)

    def stage(chunk_value, n_steps, n_real, n_samples, use_many):
        ssp = tracer.start_span(
            "stage", trace_lib.KIND_STAGE, parent=trace_parent,
            attrs={"steps": n_steps},
        )
        staged.append((chunk_value(), n_steps, n_real, n_samples, use_many))
        tracer.end_span(ssp)

    def drain_all():
        rsp = tracer.start_span(
            "readback", trace_lib.KIND_READBACK, parent=trace_parent,
            attrs={"pending": drain.inflight},
        )
        acc = drain.drain()
        tracer.end_span(rsp)
        return acc

    chunk = []
    for batch_idx, host_batch in enumerate(stalled_iter(loader, stall)):
        if inject_cb is not None:
            host_batch = inject_cb(host_batch)
        if probe_cb is not None:
            probe_cb(batch_idx, host_batch)
        tel.offer_batch(host_batch)
        if poll():
            return state, drain_all(), True
        if scan_k <= 1 and accum <= 1:
            # per-batch cadence: the staging queue still overlaps batch N+1's
            # placement with batch N's dispatch (the pre-pipeline path staged
            # nothing ahead here and paid the transfer serially). Same depth
            # semantics as the scan path: `depth` batches held staged ahead.
            stage(lambda: ddp.shard(host_batch), 1, 1, len(host_batch[1]), False)
            while len(staged) > depth or (staged and cfg.sync_readback):
                dispatch_oldest()
            continue
        chunk.append(host_batch)
        if len(chunk) == scan_k:
            stage(
                lambda c=chunk: ddp.shard_stacked(stack_batches(c)),
                scan_k,
                scan_k,
                sum(len(b[1]) for b in chunk),
                True,
            )
            chunk = []
            # keep at most `depth` chunks staged ahead; dispatch the oldest
            # beyond that (dispatch is async — the device is already busy)
            while len(staged) > depth or (staged and cfg.sync_readback):
                dispatch_oldest()
    if poll():
        return state, drain_all(), True
    while staged:
        dispatch_oldest()
    if chunk and accum > 1:
        # tail under accumulation: pad to whole cycles, one scan dispatch
        # (a per-batch step would fire a full-scale update per micro-batch)
        tail_samples = sum(len(b[1]) for b in chunk)
        n_real_tail = len(chunk)  # padding batches are not loader positions
        tail = _pad_to_cycles(chunk, accum)
        stage(
            lambda: ddp.shard_stacked(stack_batches(tail)),
            len(tail), n_real_tail, tail_samples, True,
        )
        dispatch_oldest()
        return state, drain_all(), poll()
    for host_batch in chunk:  # remainder: single steps, same semantics
        if poll():
            return state, drain_all(), True
        stage(lambda: ddp.shard(host_batch), 1, 1, len(host_batch[1]), False)
        dispatch_oldest()
    return state, drain_all(), poll()
