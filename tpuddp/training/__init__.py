"""Training layer: TrainState, compiled DP steps, epoch driver, async
pipeline, checkpointing."""

from tpuddp.training.train_state import TrainState, create_train_state  # noqa: F401
from tpuddp.training.loop import run_training_loop  # noqa: F401
from tpuddp.training.pipeline import PipelineConfig, resolve_pipeline  # noqa: F401
from tpuddp.training import checkpoint  # noqa: F401

__all__ = [
    "TrainState", "create_train_state", "run_training_loop", "checkpoint",
    "PipelineConfig", "resolve_pipeline",
]
