"""Cross-topology checkpoint resharding — the elastic mesh failover core.

A checkpoint written on one ``(data, model)`` mesh shape, reshaped into a
checkpoint for another — as pure array surgery on the saved ``.npz`` payload
(veScale's shape-consistent save/restore bar, arxiv 2509.07003). This module
is deliberately **numpy + stdlib only** at import time: the offline CLI
(``tpuddp_inspect reshard``) must run on analysis hosts and in post-mortem
tooling without dragging in jax, and :mod:`tpuddp.training.checkpoint` calls
into it lazily for the opt-in ``reshard_on_mismatch`` load path.

What the reshaper actually has to do follows from what format v3 puts on
disk (``checkpoint.py`` module doc):

- **Parameters and tree-shaped optimizer moments are stored as FULL gathered
  logical arrays** — model-width-independent bytes. Crossing a model width
  therefore never re-splits weight payloads; it rewrites the topology record
  (world/model/mesh/placement) and, at the TP<->DP *layout* boundary,
  applies the exact QKV reshape from :mod:`tpuddp.parallel.tensor`
  (``to_tp_tree``/``from_tp_tree``): ``wqkv`` ``(E, 3*H*Dh) <-> (E, 3,
  H*Dh)`` and ``bqkv`` ``(3*H*Dh,) <-> (3, H*Dh)``. A reshape is a pure
  view change — byte-identical both ways, which is what makes the
  W -> W' -> W round-trip guarantee checkable bitwise.
- **Flat data-axis vectors** (weight-update-sharded moments, the auto-mode
  error-feedback residual; tag ``data_flat``) are the raw parameter count
  zero-padded to a world multiple — re-padded to the target world's length,
  exact because the tail is zeros by construction (verified, mirroring
  ``checkpoint._refit_flat``). ``data_flat`` state only exists at model=1
  (the DDP wrapper refuses weight-update sharding under tensor parallelism),
  so a target model>1 refuses.
- **The per-(data, model)-device error-feedback residual** (tag
  ``per_replica``) is ``(world * per,)`` laid out data-major/model-minor. At
  a FIXED model width it re-pads each slice and redistributes over the data
  axis per model column, sum-preservingly when the widths share a divisor
  relation (grow-then-shrink is bitwise-exact; see
  ``tpuddp.parallel.comm.redistribute_residual``, mirrored here as
  :func:`redistribute_rows` to keep this module jax-free — a tier-1 drift
  test pins the two implementations equal). ACROSS model widths the slices
  key by unrelated model shards, so the residual is DROPPED and the loader
  re-zero-initializes it from the live template — reset semantics, recorded
  as a typed ``comm_state_reset`` action so the discontinuity is auditable.
- **Placement tags** for a model>1 target are synthesized from
  :data:`TP_PLACEMENT_RULES`, a static mirror of the live rule table
  (``tensor.tp_param_specs`` over ``transformer.PARTITION_RULES``). A tier-1
  test compares the synthesized tags against a real TP save's
  ``derive_topology`` output — placement-tag drift between this table and
  the live stack fails the gate instead of shipping.

What is REFUSED (typed :class:`ReshardError`): v1 files (no topology
record), ``data_flat`` state onto a model>1 target, model widths that do not
divide a model-split dimension (the shape-level shadow of
``validate_tp_geometry``), and flat vectors whose length does not match the
padding arithmetic their tags claim (a changed model, not a changed world).
Genuinely incompatible trees (wrong head width, wrong dtype) are *not* this
module's business — the loader's template validation still refuses them
after a reshard, and regression tests pin that.
"""

from __future__ import annotations

import json
import math
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Mirrors of the checkpoint format markers (checkpoint.py defines the same
# constants; duplicated here so this module imports without jax — a drift
# test asserts they match).
KEY_MARK = "__prngkey__"
BF16_MARK = "__bf16__"
META_MARK = "__meta__"
TOPO_MARK = "__topology__"
CURSOR_MARK = "__cursor"  # prefix of both __cursor__ and __cursor_acc__*

FORMAT_VERSION = 4  # tracks checkpoint.FORMAT_VERSION — v4 adds the
# optional __cursor__ data-cursor record; the topology record contents the
# reshaper keys on are unchanged from v3.

_MODEL_AXIS = "model"
_DATA_AXIS = "data"

# The TP<->canonical layout boundary: the two leaves tensor.to_tp_tree /
# from_tp_tree reshape. Matched by key SUFFIX so the rule covers parameters
# AND their path-congruent Adam moments (.opt_state.m/... , .opt_state.v/...)
# in both the native (".params[...]") and managed ("['params'][...]") key
# spellings.
_WQKV_SUFFIX = "['attn']['wqkv']"
_BQKV_SUFFIX = "['attn']['bqkv']"

# Static mirror of the live placement rule table: tensor.tp_param_specs
# (transformer.PARTITION_RULES under tp_rules(), plus the two QKV layout
# overrides), spelled in derive_topology's JSON form — one entry per
# model-sharded leaf suffix, [mesh-axis-or-None per dimension] in the TP
# layout. Leaves not listed are replicated over the model axis and carry no
# placement tag, exactly like derive_topology. test_reshard.py pins this
# table against a real TP save so drift fails tier-1.
TP_PLACEMENT_RULES: Tuple[Tuple[str, List[Optional[str]]], ...] = (
    ("['embed']['weight']", [_MODEL_AXIS, None]),  # vocab-split embedding/LM head
    (_WQKV_SUFFIX, [None, None, _MODEL_AXIS]),     # (E, 3, H*Dh): head split
    (_BQKV_SUFFIX, [None, _MODEL_AXIS]),
    ("['attn']['wo']", [_MODEL_AXIS, None]),       # row-split attention output
    ("['mlp']['w1']", [None, _MODEL_AXIS]),        # column-split MLP in
    ("['mlp']['b1']", [_MODEL_AXIS]),
    ("['mlp']['w2']", [_MODEL_AXIS, None]),        # row-split MLP out
)


class ReshardError(ValueError):
    """A checkpoint cannot be reshaped onto the requested ``(data, model)``
    mesh: the file predates the topology record, the target shape is
    infeasible (non-dividing model width, data_flat state under model>1), or
    the stored arrays contradict their own shard tags."""


# --------------------------------------------------------------- helpers --


def _is_param_key(key: str) -> bool:
    return key.startswith(".params") or key.startswith("['params']")


def _is_comm_key(key: str) -> bool:
    return key in (".comm_state", "['comm_state']")


def _strip_mark(key: str) -> Tuple[str, str]:
    """``(mark, bare_key)`` — npz entry name minus its dtype-encoding mark."""
    for mark in (KEY_MARK, BF16_MARK):
        if key.startswith(mark):
            return mark, key[len(mark):]
    return "", key


def parse_topology(stored: Dict[str, np.ndarray]) -> Optional[dict]:
    """The parsed ``__topology__`` record of an npz payload dict (None = v1)."""
    if TOPO_MARK not in stored:
        return None
    return json.loads(str(np.asarray(stored[TOPO_MARK]).item()))


def topology_shape(topo: dict) -> Tuple[int, int]:
    """``(data, model)`` widths recorded by a v2/v3 topology record."""
    world = int(topo.get("world_size") or 0)
    model = topo.get("model_size")
    if model is None:
        axes, shape = topo.get("mesh_axes"), topo.get("mesh_shape")
        model = (
            int(shape[list(axes).index(_MODEL_AXIS)])
            if axes and shape and _MODEL_AXIS in axes
            else 1
        )
    model = int(model)
    if world < 1 or model < 1 or world % model:
        raise ReshardError(
            f"topology record is inconsistent: world_size={world} is not a "
            f"multiple of model_size={model}"
        )
    return world // model, model


def redistribute_rows(mat: np.ndarray, new_world: int) -> Tuple[np.ndarray, str]:
    """Sum-preserving re-mapping of per-replica residual rows onto a new
    world size — a numpy-only mirror of
    :func:`tpuddp.parallel.comm.redistribute_residual` (kept in lockstep by a
    tier-1 drift test) so the offline reshaper never imports jax. Shrink
    along a divisor: consecutive row groups sum (bitwise-reproducible f32
    adds); grow along a divisor: rows place verbatim at stride ``new/old``
    with zeros between; no divisor relation: reset to zeros. Returns
    ``(new_mat, action)``."""
    mat = np.asarray(mat)
    if mat.ndim != 2:
        raise ValueError(f"expected a (world, per) residual view, got {mat.shape}")
    old_world, per = mat.shape
    if new_world < 1:
        raise ValueError(f"new_world must be >= 1, got {new_world}")
    if new_world == old_world:
        return mat, "unchanged"
    if old_world % new_world == 0:
        k = old_world // new_world
        return mat.reshape(new_world, k, per).sum(axis=1), "redistributed"
    if new_world % old_world == 0:
        k = new_world // old_world
        out = np.zeros((new_world, per), mat.dtype)
        out[::k] = mat
        return out, "redistributed"
    return np.zeros((new_world, per), mat.dtype), "reset"


def _padded_total(raw: int, world: int) -> int:
    """``step.make_flat_param_spec``'s padding rule: raw element count
    rounded up to a world multiple."""
    return world * math.ceil(raw / world)


def _placement_for(key: str, placement: Dict[str, list]) -> Optional[list]:
    return placement.get(key)


def _model_split_dims(key: str, axes: Optional[list]) -> List[int]:
    """Dimensions of ``key``'s array that the placement tag splits over the
    model axis (an entry may be a single axis name or a list of axes)."""
    if not axes:
        return []
    out = []
    for d, entry in enumerate(axes):
        names = entry if isinstance(entry, (list, tuple)) else [entry]
        if any(n == _MODEL_AXIS for n in names if n):
            out.append(d)
    return out


def _local_param_numel(
    bare_keys: Dict[str, Tuple[str, np.ndarray]],
    placement: Dict[str, list],
    model: int,
) -> int:
    """Element count of ONE model shard's parameter tree — the ``raw`` the
    gradient-comm flat spec pads from (``local_param_template`` shapes:
    model-split dimensions divided by the width)."""
    raw = 0
    for key, (mark, arr) in bare_keys.items():
        if not _is_param_key(key) or mark == KEY_MARK:
            continue
        n = int(np.prod(arr.shape, dtype=np.int64)) if arr.ndim else 1
        for d in _model_split_dims(key, placement.get(key)):
            if d >= arr.ndim:
                raise ReshardError(
                    f"parameter leaf {key!r} placement names dimension {d} "
                    f"but the stored array has shape {tuple(arr.shape)}"
                )
            size = int(arr.shape[d])
            if size % model:
                raise ReshardError(
                    f"parameter leaf {key!r} dimension {d} (size {size}) is "
                    f"recorded model-split but does not divide model={model}"
                )
            n //= model
        raw += n
    return raw


def _synth_placement(key: str, arr: np.ndarray, model: int) -> Optional[list]:
    """Placement tag for ``key`` on a model>1 target, from the static rule
    table. None = replicated over the model axis (no tag), matching
    ``derive_topology``'s omission of fully-replicated leaves."""
    if _is_comm_key(key):
        return [[_DATA_AXIS, _MODEL_AXIS]]
    if not (_is_param_key(key) or key.startswith(".opt_state")
            or key.startswith("['opt_state']")):
        return None
    for suffix, axes in TP_PLACEMENT_RULES:
        if key.endswith(suffix):
            if len(axes) != arr.ndim:
                raise ReshardError(
                    f"leaf {key!r} has {arr.ndim} dimensions but the TP "
                    f"placement rule table expects {len(axes)} — layout "
                    "reshape missing or table drift"
                )
            # PartitionSpec drops trailing None entries, so derive_topology
            # records ("model", None) as ["model"] — trim to match the live
            # tags bitwise (the drift test compares dict-equal).
            out = list(axes)
            while out and out[-1] is None:
                out.pop()
            return out
    return None


def _reshape_qkv(key: str, arr: np.ndarray, to_tp: bool) -> np.ndarray:
    """The exact tensor.to_tp_tree/from_tp_tree reshape for one QKV leaf —
    applied to f32 payloads and uint16 bf16 bit views alike (a reshape never
    touches bytes)."""
    if key.endswith(_WQKV_SUFFIX):
        if to_tp:
            if arr.ndim != 2 or arr.shape[1] % 3:
                raise ReshardError(
                    f"leaf {key!r} has shape {arr.shape}; expected canonical "
                    "(E, 3*H*Dh) joined QKV to enter the TP layout"
                )
            return arr.reshape(arr.shape[0], 3, arr.shape[1] // 3)
        if arr.ndim != 3 or arr.shape[1] != 3:
            raise ReshardError(
                f"leaf {key!r} has shape {arr.shape}; expected TP-layout "
                "(E, 3, H*Dh) joined QKV to leave the TP layout"
            )
        return arr.reshape(arr.shape[0], arr.shape[1] * arr.shape[2])
    if key.endswith(_BQKV_SUFFIX):
        if to_tp:
            if arr.ndim != 1 or arr.shape[0] % 3:
                raise ReshardError(
                    f"leaf {key!r} has shape {arr.shape}; expected canonical "
                    "(3*H*Dh,) joined QKV bias to enter the TP layout"
                )
            return arr.reshape(3, arr.shape[0] // 3)
        if arr.ndim != 2 or arr.shape[0] != 3:
            raise ReshardError(
                f"leaf {key!r} has shape {arr.shape}; expected TP-layout "
                "(3, H*Dh) joined QKV bias to leave the TP layout"
            )
        return arr.reshape(arr.shape[0] * arr.shape[1])
    return arr


# ------------------------------------------------------------------ core --


def reshard_arrays(
    stored: Dict[str, np.ndarray],
    data: int,
    model: int,
    path: str = "<memory>",
) -> Tuple[Dict[str, np.ndarray], dict, List[dict]]:
    """Reshape a saved npz payload from its recorded ``(data, model)`` mesh
    onto the target one. Returns ``(new_stored, new_topology, actions)`` —
    ``new_stored`` includes the rewritten ``__topology__`` entry and every
    ``__meta__*`` scalar untouched; ``actions`` is shaped for
    ``checkpoint.build_reshard_events`` (one dict per touched leaf).

    Same-shape targets return the payload unchanged (idempotent), which is
    what makes the W -> W' -> W round-trip byte-comparable."""
    topo = parse_topology(stored)
    if topo is None:
        raise ReshardError(
            f"checkpoint {path} predates the topology record (format v1) and "
            "carries no shard provenance to reshard from; re-save it through "
            "save_on_main (which records format v3) first"
        )
    data, model = int(data), int(model)
    if data < 1 or model < 1:
        raise ReshardError(f"target mesh data={data} model={model} is not a mesh")
    from_data, from_model = topology_shape(topo)
    world = data * model
    actions: List[dict] = []
    if (from_data, from_model) == (data, model):
        return dict(stored), topo, actions

    placement: Dict[str, list] = dict(topo.get("placement") or {})
    leaves: Dict[str, dict] = dict(topo.get("leaves") or {})

    # bare-key view of the payload: {bare: (mark, array)}
    bare: Dict[str, Tuple[str, np.ndarray]] = {}
    passthrough: Dict[str, np.ndarray] = {}
    for k, v in stored.items():
        if k == TOPO_MARK or k.startswith(META_MARK) or k.startswith(CURSOR_MARK):
            # the v4 data cursor (and its accumulator arrays) is bookkeeping,
            # not model state — it passes through unreshaped; restore_latest
            # poisons a resharded cursor's plan key so the driver redoes the
            # epoch instead of skipping wrong batches
            passthrough[k] = v
            continue
        mark, bk = _strip_mark(k)
        bare[bk] = (mark, np.asarray(v))

    # 1. TP<->canonical layout boundary: the QKV reshape (bitwise).
    crossing_down = from_model > 1 and model == 1   # TP layout -> canonical
    crossing_up = from_model == 1 and model > 1     # canonical -> TP layout
    if crossing_down or crossing_up:
        for bk in list(bare):
            mark, arr = bare[bk]
            if mark == KEY_MARK:
                continue
            new = _reshape_qkv(bk, arr, to_tp=crossing_up)
            if new is not arr:
                bare[bk] = (mark, new)
                actions.append({
                    "leaf": bk, "action": "relayout",
                    "from_shape": list(arr.shape), "to_shape": list(new.shape),
                })

    # 2. Placement tags for the target: keep (model>1 -> model>1), drop the
    # model axis (-> model=1), or synthesize from the rule table (model=1 ->
    # model>1). Data-axis-only tags (flat vectors) survive every crossing.
    new_placement: Dict[str, list] = {}
    if model == from_model:
        new_placement = dict(placement)
    elif model == 1:
        for key, axes in placement.items():
            kept = []
            for entry in axes:
                names = entry if isinstance(entry, (list, tuple)) else [entry]
                names = [n for n in names if n and n != _MODEL_AXIS]
                kept.append(
                    None if not names
                    else (names[0] if len(names) == 1 else names)
                )
            if any(a is not None for a in kept):
                new_placement[key] = kept
    else:
        for bk, (mark, arr) in bare.items():
            if mark == KEY_MARK:
                continue
            axes = _synth_placement(bk, arr, model)
            if axes is not None:
                new_placement[bk] = axes

    # 3. Feasibility: every model-split dimension must divide the target
    # width (the shape-level shadow of validate_tp_geometry — heads, d_mlp,
    # vocab divisibility all surface here as a named leaf).
    if model > 1:
        for key, axes in new_placement.items():
            if key not in bare:
                continue
            arr = bare[key][1]
            for d in _model_split_dims(key, axes):
                if d >= arr.ndim or int(arr.shape[d]) % model:
                    raise ReshardError(
                        f"checkpoint {path}: leaf {key!r} dimension {d} "
                        f"(shape {tuple(arr.shape)}) does not divide the "
                        f"target model width {model} — this mesh shape is "
                        "infeasible for the saved architecture"
                    )

    # 4. Shape-dependent flat state: data_flat re-pad, per_replica
    # redistribute/reset.
    new_leaves: Dict[str, dict] = {}
    raw_from = raw_to = None  # lazy: only flat leaves need the param counts
    dropped: List[str] = []
    for key, info in leaves.items():
        if key not in bare:
            continue  # tag for a leaf this payload doesn't carry
        mark, arr = bare[key]
        kind = info.get("kind")
        if kind == "data_flat":
            if model > 1:
                raise ReshardError(
                    f"checkpoint {path}: flat data-axis leaf {key!r} "
                    "(weight-update-sharded moments / auto-mode residual) "
                    "has no tensor-parallel layout — the DDP wrapper refuses "
                    "weight_update_sharding under model>1, so there is no "
                    "model>1 target to reshard onto. Restore at model=1."
                )
            if raw_from is None:
                raw_from = _local_param_numel(bare, placement, from_model)
            if int(arr.shape[0]) != _padded_total(raw_from, from_data * from_model):
                raise ReshardError(
                    f"checkpoint {path}: flat leaf {key!r} has "
                    f"{arr.shape[0]} elements but the recorded topology "
                    f"implies {_padded_total(raw_from, from_data * from_model)} "
                    f"({raw_from} raw padded to a world multiple) — the "
                    "model changed, not just the mesh shape"
                )
            total = _padded_total(raw_from, world)
            if total != int(arr.shape[0]):
                if total < arr.shape[0] and np.any(arr[total:]):
                    raise ReshardError(
                        f"checkpoint {path}: flat leaf {key!r} carries "
                        f"non-zero data past {total} — not world-multiple "
                        "padding"
                    )
                out = np.zeros((total,), arr.dtype)
                keep = min(total, int(arr.shape[0]))
                out[:keep] = arr[:keep]
                bare[key] = (mark, out)
                actions.append({
                    "leaf": key, "action": "repadded",
                    "from_shape": [int(arr.shape[0])], "to_shape": [total],
                })
            new_leaves[key] = dict(info)
        elif kind == "per_replica":
            n_from, per_from = int(info["world"]), int(info["per"])
            if int(arr.shape[0]) != n_from * per_from:
                raise ReshardError(
                    f"checkpoint {path}: per-replica leaf {key!r} has "
                    f"{arr.shape[0]} elements but its topology record says "
                    f"{n_from} x {per_from}"
                )
            if from_model != model:
                # slices key by (data_index, model_index); across model
                # widths they describe unrelated model shards — DROP the
                # leaf, the loader re-zero-initializes from its live
                # template (reset semantics), and the action row makes the
                # discontinuity auditable as a comm_state_reset event.
                del bare[key]
                new_placement.pop(key, None)
                dropped.append(key)
                actions.append({
                    "leaf": key, "action": "reset",
                    "from_world": n_from, "to_world": world,
                    "reason": "error-feedback residual slices key by model "
                    "shard; a model-width change resets them to zero",
                })
                continue
            if raw_from is None:
                raw_from = _local_param_numel(bare, placement, from_model)
            per_to = _padded_total(raw_from, data)
            mat = arr.reshape(from_data, model, per_from)
            if per_from != per_to:
                if per_from > per_to and np.any(mat[:, :, per_to:]):
                    raise ReshardError(
                        f"checkpoint {path}: per-replica leaf {key!r} "
                        f"carries non-zero data past the target per-replica "
                        f"length {per_to} — not world-multiple padding"
                    )
                cols = np.zeros((from_data, model, per_to), arr.dtype)
                keep = min(per_from, per_to)
                cols[:, :, :keep] = mat[:, :, :keep]
                mat = cols
            new_cols = []
            action = "unchanged"
            for m in range(model):
                col, action = redistribute_rows(mat[:, m, :], data)
                new_cols.append(col)
            out = np.stack(new_cols, axis=1).reshape(-1)
            bare[key] = (mark, out)
            new_leaves[key] = {
                "kind": "per_replica", "world": world, "per": per_to,
                "model": model,
            }
            act = {
                "leaf": key, "action": action,
                "from_world": n_from, "to_world": world,
            }
            if action == "reset":
                act["reason"] = (
                    "no divisor relation between data widths; error-feedback "
                    "residual reset to zero"
                )
            if action != "unchanged" or per_from != per_to:
                if action == "unchanged":
                    act["action"] = "repadded"
                actions.append(act)
        else:
            raise ReshardError(
                f"checkpoint {path}: leaf {key!r} has unknown shard tag "
                f"{info!r}"
            )

    # 5. The rewritten topology record.
    new_topo = {
        "format": FORMAT_VERSION,
        "world_size": world,
        "model_size": model,
        "mesh_axes": [_DATA_AXIS, _MODEL_AXIS] if model > 1 else [_DATA_AXIS],
        "mesh_shape": [data, model] if model > 1 else [data],
        "leaves": new_leaves,
        "placement": new_placement,
        "resharded": {
            "from": [from_data, from_model],
            "to": [data, model],
            "dropped": dropped,
        },
    }

    new_stored: Dict[str, np.ndarray] = {}
    for bk, (mark, arr) in bare.items():
        new_stored[mark + bk] = arr
    for k, v in passthrough.items():
        if k != TOPO_MARK:
            new_stored[k] = v
    new_stored[TOPO_MARK] = np.asarray(json.dumps(new_topo))
    return new_stored, new_topo, actions


def reshard_checkpoint(src: str, dst: str, data: int, model: int) -> dict:
    """File-level wrapper: load ``src``, reshard onto ``(data, model)``,
    publish ``dst`` atomically (tmp + replace) with a fresh ``.sha256``
    manifest. Returns a report dict (shapes, actions, leaf count) for the
    CLI / gate to print."""
    with np.load(src) as f:
        stored = dict(f.items())
    topo = parse_topology(stored)
    from_shape = topology_shape(topo) if topo else None
    new_stored, new_topo, actions = reshard_arrays(
        stored, data, model, path=src
    )
    tmp = dst + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **new_stored)
    os.replace(tmp, dst)  # atomic publish, same discipline as checkpoint.save
    _integrity().write_manifest(dst)
    return {
        "src": src,
        "dst": dst,
        "from": {"data": from_shape[0], "model": from_shape[1]},
        "to": {"data": data, "model": model},
        "actions": actions,
        "leaves": sum(
            1 for k in new_stored
            if k != TOPO_MARK and not k.startswith(META_MARK)
        ),
    }


def _integrity():
    """The integrity module without forcing ``import tpuddp`` (whose package
    __init__ pulls jax): try the package import, fall back to loading the
    stdlib-only file directly — offline hosts get manifests either way."""
    try:
        from tpuddp.resilience import integrity
        return integrity
    except Exception:
        import importlib.util

        p = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            os.pardir, "resilience", "integrity.py",
        )
        spec = importlib.util.spec_from_file_location("_tpuddp_integrity", p)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod
