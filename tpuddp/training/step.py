"""Compiled data-parallel train/eval steps.

The reference's hot loop (multi-GPU-training-torch.py:109-132) — H2D copy,
zero_grad, forward, loss, backward (NCCL grad allreduce via DDP hooks),
optimizer step, ``loss.item()`` — becomes ONE jitted function here. Two
construction modes, both over the same mesh/collectives backend:

- ``mode="shard_map"`` — the *explicit* analog of native DDP: a per-replica
  function in which the gradient averaging is a visible ``lax.pmean`` over the
  ``"data"`` axis (exactly DDP's bucketed allreduce contract, SURVEY.md §2b
  #13), BatchNorm syncs stats with ``lax.pmean`` when converted (SyncBatchNorm
  contract), and metrics come back as per-replica partial sums — the analog of
  the reference's device-tensor accumulators that get ``dist.all_reduce``-d at
  epoch end (:198-204).

- ``mode="auto"`` — the *managed* analog (what the accelerate entrypoint
  routes through): plain global-batch code under ``jit`` with NamedShardings;
  XLA derives the same psum from the mean-loss data flow. BatchNorm statistics
  are global-batch by construction here.

Batches are ``(x, y, w)`` with a per-sample weight/mask so final partial
batches can be padded to a static shape (TPU-first: no recompiles) while the
sample-weighted metric math of the reference (:129-132) stays exact.

Optional ``augment`` / ``transform`` hooks run *inside* the step on device —
this is where tpuddp's CIFAR pipeline does resize/flip/normalize on-chip,
fused into the forward pass by XLA.
"""

from __future__ import annotations

import math
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from tpuddp import optim as _optim
from tpuddp.nn.core import Context
from tpuddp.parallel import collectives as col
from tpuddp.resilience import guard as guard_lib
from tpuddp.utils.compat import shard_map
from tpuddp.parallel.mesh import DATA_AXIS, data_axes, data_sharded, replicated
from tpuddp.seeding import fold_in_axis_index
from tpuddp.training.train_state import TrainState


class FlatParamSpec(NamedTuple):
    """Static flattening metadata for weight-update sharding: the parameter
    pytree viewed as ONE f32 vector, zero-padded to a ``world``-multiple so
    every replica owns an equal contiguous shard."""

    treedef: Any
    shapes: Tuple[Tuple[int, ...], ...]
    sizes: Tuple[int, ...]
    total: int  # padded length (world * shard size)
    world: int


def make_flat_param_spec(params, world: int) -> FlatParamSpec:
    flat, treedef = jax.tree_util.tree_flatten(params)
    for i, leaf in enumerate(flat):
        if jnp.asarray(leaf).dtype != jnp.float32:
            raise ValueError(
                "weight_update_sharding flattens parameters into one f32 "
                f"vector; leaf {i} has dtype {jnp.asarray(leaf).dtype} "
                "(tpuddp keeps f32 master params — mixed compute dtypes live "
                "in activations, not parameters)"
            )
    shapes = tuple(tuple(int(d) for d in np.shape(l)) for l in flat)
    sizes = tuple(int(np.prod(s)) if s else 1 for s in shapes)
    raw = sum(sizes)
    total = world * math.ceil(raw / world)
    return FlatParamSpec(treedef, shapes, sizes, total, world)


def _tree_to_vec(tree, spec: FlatParamSpec):
    """Concatenate a pytree's leaves (ravel order = tree_flatten order) into
    the spec's padded (total,) f32 vector."""
    leaves = jax.tree_util.tree_leaves(tree)
    flat = jnp.concatenate([jnp.ravel(l) for l in leaves])
    pad = spec.total - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _vec_to_tree(vec, spec: FlatParamSpec):
    leaves, offset = [], 0
    for shape, size in zip(spec.shapes, spec.sizes):
        chunk = jax.lax.slice(vec, (offset,), (offset + size,))
        leaves.append(chunk.reshape(shape))
        offset += size
    return jax.tree_util.tree_unflatten(spec.treedef, leaves)


def sharded_state_spec(opt_state_template, spec: FlatParamSpec, comm=None,
                       axis=DATA_AXIS):
    """The shard_map PartitionSpec pytree for a TrainState whose optimizer
    moment vectors are sharded over the data axis (weight-update sharding):
    every (total,)-sized 1-D leaf of the optimizer state is P(axis),
    everything else replicated. ``comm`` (a GradComm with an error-feedback
    residual) additionally marks ``comm_state`` sharded — the residual is
    per-replica local state, laid out like the moment shards. ``axis`` is
    the data axis name (a tuple on the factored hierarchical mesh)."""
    def leaf_spec(l):
        if getattr(l, "ndim", None) == 1 and l.shape[0] == spec.total:
            return P(axis)
        return P()

    opt_spec = jax.tree_util.tree_map(leaf_spec, opt_state_template)
    return TrainState(
        params=P(), model_state=P(), opt_state=opt_spec, step=P(), rng=P(),
        comm_state=(
            P(axis) if comm is not None and comm.needs_residual else P()
        ),
        skipped_steps=P(),  # guard counters replicate (P() is a safe prefix
        # for the empty subtree when the guard is off)
    )


def comm_state_spec(axis=DATA_AXIS):
    """The shard_map PartitionSpec pytree for a TrainState whose ONLY sharded
    member is the per-replica comm-hook residual (an EF hook without
    weight-update sharding): everything replicated except ``comm_state``."""
    return TrainState(
        params=P(), model_state=P(), opt_state=P(), step=P(), rng=P(),
        comm_state=P(axis), skipped_steps=P(),
    )


def _split_step_rng(state: TrainState, axis_name: Optional[str]):
    """Per-step key; inside shard_map additionally fold in the replica index so
    dropout/augmentation masks differ across replicas (device-level rank fold,
    mirroring the reference's per-rank seeds)."""
    rng = jax.random.fold_in(state.rng, state.step)
    if axis_name is not None:
        rng = fold_in_axis_index(rng, axis_name)
    return jax.random.split(rng)


_SYNC_BUFFER_MODES = ("broadcast", "pmean", "none")


def _validate_sync_buffers(model, axis_name: Optional[str], sync_buffers: str):
    """Build-time honesty check: the shard_map step publishes ``model_state``
    with a replicated out_spec, so any config that would let per-replica
    buffers diverge silently must be refused here, not discovered as a wrong
    checkpoint later."""
    if sync_buffers not in _SYNC_BUFFER_MODES:
        raise ValueError(
            f"unknown sync_buffers {sync_buffers!r}; one of {_SYNC_BUFFER_MODES}"
        )
    if axis_name is not None and sync_buffers == "none":
        from tpuddp.nn.norm import has_divergent_buffers

        if has_divergent_buffers(model):
            raise ValueError(
                'sync_buffers="none" with a module whose buffers diverge '
                "across replicas (an unsynced stateful BatchNorm, or a "
                "custom stateful layer that does not declare "
                "divergent_state()): per-replica state would diverge but be "
                "published as replicated. Use sync_buffers='broadcast' "
                "(torch DDP's broadcast_buffers=True default), 'pmean', "
                "convert_sync_batchnorm(model), or declare "
                "divergent_state() -> False on the module if its state is "
                "replica-invariant."
            )


def _make_grad_core(
    model,
    criterion,
    axis_name: Optional[str],
    sync_buffers: str,
    augment: Optional[Callable],
    remat: bool = False,
):
    """The forward+backward half of the train step: one micro-batch in,
    ``(grads, synced_model_state, loss, n)`` out. Gradients are this replica's
    LOCAL batch-mean gradient — cross-replica reduction belongs to the update
    half (:func:`_make_update_fn`), so gradient accumulation can sum local
    grads over K micro-batches and pay for ONE collective per cycle."""
    # Rematerialization: trade FLOPs for HBM by recomputing activations in the
    # backward pass (jax.checkpoint) — how large models/batches fit on-chip.
    apply_fn = model.apply
    if remat:
        def apply_fn(params, mstate, x, ctx):  # noqa: F811
            fn = jax.checkpoint(
                lambda p, s, v: model.apply(p, s, v, ctx),
                static_argnums=(),
            )
            return fn(params, mstate, x)

    def grad_core(state: TrainState, x, y, w):
        aug_rng, dropout_rng = _split_step_rng(state, axis_name)
        if augment is not None:
            x = augment(aug_rng, x)

        def loss_fn(params):
            # sample_weight masks padded rows out of BatchNorm statistics,
            # not just loss/metrics (see nn/norm.py)
            ctx = Context(
                train=True, rng=dropout_rng, axis_name=axis_name, sample_weight=w
            )
            logits, model_state = apply_fn(params, state.model_state, x, ctx)
            loss = criterion(logits, y, w)
            return loss, model_state

        (loss, model_state), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )

        if axis_name is not None and sync_buffers == "broadcast":
            # torch DDP's default broadcast_buffers=True: unsynced BN buffers
            # follow rank 0. Synced BN already produced identical buffers.
            model_state = col.broadcast(model_state, root=0, axis_name=axis_name)
        elif axis_name is not None and sync_buffers == "pmean":
            # average instead of rank-0-wins: every replica's statistics
            # contribute (identical when BN is already synced)
            model_state = col.pmean(model_state, axis_name)

        return grads, model_state, loss, jnp.sum(w)

    return grad_core


def _firewall_gate(ok, do_update, params, opt_state, comm_state, skipped):
    """The lax.cond firewall gate: ``do_update() -> (params, opt, comm)``
    executes only on a finite aggregated gradient; the skip branch hands
    the inputs back bitwise (the EF residual included — its NaN-poisoned
    candidate is never materialized into the carry) and bumps the
    counters. ``consecutive`` resets on every applied update. Shared by
    the barrier update (:func:`_make_update_fn`) and the segmented-overlap
    tail (:func:`_make_apply_reduced`) so both lower to the same cond."""

    def _apply():
        new_params, new_opt_state, new_comm = do_update()
        return (
            new_params, new_opt_state, new_comm,
            guard_lib.reset_consecutive(skipped),
        )

    def _skip():
        return (
            params, opt_state, comm_state,
            guard_lib.bump_skip_counters(skipped),
        )

    return jax.lax.cond(ok, _apply, _skip)


def _make_update_fn(
    optimizer,
    axis_name,
    clip_grad_norm: Optional[float],
    wus_spec: Optional[FlatParamSpec],
    comm=None,
    guard: bool = False,
    hier: Optional[Tuple[str, str]] = None,
):
    """The optimizer half of the train step: replica-local mean gradients in,
    ``(new_params, new_opt_state, new_comm_state, new_skipped)`` out. Owns
    the cross-replica exchange (pmean, a compressed bucketed exchange when a
    comm hook is configured, or reduce-scatter/all-gather under
    weight-update sharding) and the clip-after-aggregate. ``comm`` is a
    :class:`tpuddp.parallel.comm.GradComm` plan (None or hook "none" keeps
    the legacy full-precision path byte-identical); ``comm_state`` threads
    the error-feedback residual through the step. ``hier=(inner, outer)``
    routes the exchange through the hierarchical multi-hop reduction
    (``comm_topology="hierarchical"``: intra-host f32 reduce-scatter over
    ``inner``, compressed inter-host exchange over ``outer``, all-gather —
    requires a ``comm`` plan, which may carry hook "none").

    ``guard=True`` arms the non-finite gradient firewall
    (resilience/guard.py): ONE fused finiteness reduction over the
    aggregated f32 gradient — post-allreduce, so a NaN/Inf on any replica
    propagates through the sum and every replica agrees on the verdict by
    construction; with a comm hook the check runs on the decompressed f32
    payload (auto mode checks before quantization, where the aggregate
    already exists) — gates clip + optimizer.update through ``lax.cond``. A
    bad step is a bitwise no-op on params/opt-state/EF-residual and bumps
    the ``skipped_steps`` counters. ``guard=False`` is the pre-guard code
    path verbatim (identical HLO, ``skipped`` passes through untouched)."""

    gate = _firewall_gate

    def apply_update(params, opt_state, grads, comm_state, skipped):
        if wus_spec is not None:
            # Weight-update sharding (the cross-replica weight-update recipe
            # of arxiv.org/abs/2004.13336, ZeRO-1's TPU-native shape): instead
            # of every replica all-reducing the full gradient and redundantly
            # running the identical optimizer update over ALL parameters,
            # reduce-scatter hands each replica the averaged gradient for its
            # 1/N contiguous shard of the flattened parameter vector; each
            # replica updates only that shard (with its 1/N slice of the
            # optimizer moments — m/v live SHARDED across the mesh, an N-fold
            # optimizer-memory and update-HBM-traffic saving); the new shards
            # are all-gathered back into replicated parameters over ICI.
            # Same bytes on the interconnect as the allreduce (scatter+gather
            # IS an allreduce), 1/N of the optimizer's HBM round trip.
            world = wus_spec.world
            shard_n = wus_spec.total // world
            g_vec = _tree_to_vec(grads, wus_spec)
            if comm is not None and comm.compressed:
                # comm-hook composition: scatter the COMPRESSED payload —
                # half the gradient wire bytes; the bf16_ef residual stays
                # full-length and replica-local (see comm.reduce_scatter)
                g_shard, new_comm = comm.reduce_scatter(
                    g_vec, comm_state, axis_name
                )
            else:
                g_shard = (
                    jax.lax.psum_scatter(
                        g_vec, axis_name, scatter_dimension=0, tiled=True
                    )
                    / world
                )
                new_comm = comm_state

            def wus_update(g_shard=g_shard, new_comm=new_comm):
                g = g_shard
                if clip_grad_norm is not None:
                    # the global norm of a sharded vector is one scalar psum
                    # away; padding zeros contribute nothing
                    norm = jnp.sqrt(
                        jax.lax.psum(jnp.sum(jnp.square(g)), axis_name)
                    )
                    g = g * jnp.minimum(1.0, clip_grad_norm / (norm + 1e-6))
                idx = jax.lax.axis_index(axis_name)
                p_vec = _tree_to_vec(params, wus_spec)
                p_shard = jax.lax.dynamic_slice(
                    p_vec, (idx * shard_n,), (shard_n,)
                )
                update_flat = getattr(optimizer, "update_flat", None)
                if update_flat is not None:
                    # layer-boundary-aware flat update (LARS/LAMB trust
                    # ratios over the spec's leaf offsets; per-layer norms
                    # psum across the axis since shards straddle layers)
                    new_p_shard, new_opt_state = update_flat(
                        g, opt_state, p_shard, spec=wus_spec,
                        axis_name=axis_name, shard_index=idx,
                    )
                else:
                    new_p_shard, new_opt_state = optimizer.update(
                        g, opt_state, p_shard
                    )
                new_p_vec = jax.lax.all_gather(
                    new_p_shard, axis_name, tiled=True
                )
                return _vec_to_tree(new_p_vec, wus_spec), new_opt_state, new_comm

            if not guard:
                new_params, new_opt_state, new_comm = wus_update()
                return new_params, new_opt_state, new_comm, skipped
            # the scattered shards of the aggregated gradient live on
            # different replicas, so the local shard verdict must be agreed
            # globally: one scalar pmin next to the scatter. Every other
            # collective (clip psum, all-gather) sits inside the cond — all
            # replicas take the same branch, so they still pair up.
            ok = (
                col.pmin(
                    guard_lib.tree_all_finite(g_shard).astype(jnp.int32),
                    axis_name,
                )
                == 1
            )
            return gate(ok, wus_update, params, opt_state, comm_state, skipped)

        ok = None
        if guard and axis_name is None:
            # auto/managed mode: XLA's partitioner already aggregated inside
            # backward — `grads` IS the global-batch f32 gradient, checked
            # here BEFORE the hook quantizes it (the f32-payload contract)
            ok = guard_lib.tree_all_finite(grads)
        if hier is not None and comm is not None:
            # hierarchical multi-hop reduction over the factored data mesh:
            # intra-host f32 reduce-scatter -> compressed inter-host
            # exchange -> all-gather (comm.reduce_hierarchical)
            agg_grads, new_comm = comm.reduce_hierarchical(
                grads, comm_state, hier[0], hier[1]
            )
        elif comm is not None and comm.compressed:
            # bucketed compressed allreduce (torch DDP comm-hook analog):
            # flatten -> per-bucket compress -> collective -> f32 decompress
            # -> mean. With axis_name=None (auto mode) this is the local
            # quantization emulation — XLA's implicit psum already aggregated.
            agg_grads, new_comm = comm.reduce(grads, comm_state, axis_name)
        elif axis_name is not None:
            # THE DDP step: average gradients across replicas (reference
            # :125's implicit NCCL allreduce). In auto mode XLA inserts
            # this itself.
            agg_grads, new_comm = col.pmean(grads, axis_name), comm_state
        else:
            agg_grads, new_comm = grads, comm_state
        if guard and ok is None:
            # post-allreduce f32 gradient: the sum propagated any replica's
            # NaN/Inf everywhere, so this replica-local check IS the global
            # verdict — no extra collective on the replicated path. (bf16
            # keeps the f32 exponent range, so quantization cannot mask a
            # non-finite f32 payload from the post-reduce check.)
            ok = guard_lib.tree_all_finite(agg_grads)

        def plain_update(agg_grads=agg_grads, new_comm=new_comm):
            g = agg_grads
            if clip_grad_norm is not None:
                # clip-before-aggregate caveat (reference README): clip the
                # *averaged* grad, identically on all replicas.
                g, _ = _optim.clip_grad_norm_(g, clip_grad_norm)
            new_params, new_opt_state = optimizer.update(g, opt_state, params)
            return new_params, new_opt_state, new_comm

        if not guard:
            new_params, new_opt_state, new_comm = plain_update()
            return new_params, new_opt_state, new_comm, skipped
        return gate(ok, plain_update, params, opt_state, comm_state, skipped)

    return apply_update


def _make_train_core(
    model,
    criterion,
    optimizer,
    axis_name,
    sync_buffers: str,
    clip_grad_norm: Optional[float],
    augment: Optional[Callable],
    remat: bool = False,
    wus_spec: Optional[FlatParamSpec] = None,
    comm=None,
    guard: bool = False,
    hier: Optional[Tuple[str, str]] = None,
):
    _validate_sync_buffers(model, axis_name, sync_buffers)
    if wus_spec is not None and axis_name is None:
        raise ValueError(
            "weight_update_sharding needs the explicit per-replica step "
            "(mode='shard_map'): the reduce-scatter/all-gather exchange is "
            "expressed over its named data axis"
        )
    grad_core = _make_grad_core(
        model, criterion, axis_name, sync_buffers, augment, remat
    )
    apply_update = _make_update_fn(
        optimizer, axis_name, clip_grad_norm, wus_spec, comm=comm, guard=guard,
        hier=hier,
    )

    def core(state: TrainState, x, y, w):
        grads, model_state, loss, n = grad_core(state, x, y, w)
        new_params, new_opt_state, new_comm, new_skipped = apply_update(
            state.params, state.opt_state, grads, state.comm_state,
            state.skipped_steps,
        )
        if guard:
            # extend the no-op to the module buffers: BatchNorm running
            # stats computed from the poisoned forward must not outlive the
            # skipped update (the counters move only on a skip, so the
            # select is exactly the firewall's verdict)
            skipped_now = new_skipped["total"] != state.skipped_steps["total"]
            model_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(skipped_now, old, new),
                state.model_state, model_state,
            )
        metrics = {
            "loss_sum": (loss * n)[None],  # sample-weighted, reference :131
            "n": n[None],
        }
        new_state = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt_state,
            step=state.step + 1,
            rng=state.rng,
            comm_state=new_comm,
            skipped_steps=new_skipped,
        )
        return new_state, metrics

    return core


# -- segmented-backward execution (``comm_overlap``) ------------------------
#
# torch DDP's ready-bucket overlap, expressed natively in JAX: the backward
# pass is staged as per-segment VJP closures whose boundaries align with the
# bucket assembly (comm.make_segments), and each segment's gradient collective
# is issued the moment its buckets materialize — in trace order BEFORE the
# previous segment's backward compute, so the lowered HLO carries K
# interleaved collectives instead of one trailing block and the latency-hiding
# scheduler can overlap wire time with MXU time. Bitwise-identical to the
# barrier step by construction: the same layer VJPs over the same operands,
# the same per-bucket exchange over the same flat offsets, the same /world,
# residual, guard-verdict, clip and optimizer arithmetic — only the
# *instruction order* changes.


def _validate_segments(segments, mode: str, wus_spec, hier):
    """Builder-level honesty check for ``segments``: the segmented step only
    exists where the exchange is an explicit per-bucket op (shard_map, flat
    topology, no weight-update sharding). DDP._resolve_overlap routes
    ineligible configs to the barrier step before we get here; this guards
    direct builder callers."""
    if segments is None:
        return
    if mode != "shard_map":
        raise ValueError(
            "segments= (comm_overlap) needs mode='shard_map': the auto path's "
            "collective is inserted by XLA, not issued per segment"
        )
    if wus_spec is not None:
        raise ValueError(
            "segments= (comm_overlap) does not compose with "
            "weight_update_sharding: per-segment reduce-scatter pieces do not "
            "reassemble into the replica's canonical full-vector shard"
        )
    if hier is not None:
        raise ValueError(
            "segments= (comm_overlap) does not compose with "
            "comm_topology='hierarchical': per-segment scatter would move the "
            "error-feedback residual's owner placement"
        )
    if not segments:
        raise ValueError("segments= must be a non-empty tuple of CommSegment")


def _subtree_to_vec(tree, width: int):
    """Concatenate a params-subtree's leaves (tree_flatten order) into a flat
    f32 vector, zero-padded to ``width`` — the segment-sized sibling of
    :func:`_tree_to_vec` (only the LAST segment carries the spec's tail
    padding, so the per-segment concatenation reproduces the full padded
    vector element for element)."""
    leaves = jax.tree_util.tree_leaves(tree)
    if not leaves:
        return jnp.zeros((width,), jnp.float32)
    flat = jnp.concatenate([jnp.ravel(l) for l in leaves])
    pad = width - flat.shape[0]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat


def _make_apply_reduced(optimizer, clip_grad_norm: Optional[float], guard: bool):
    """The optimizer tail over an ALREADY cross-replica-reduced gradient:
    verdict + clip + update behind the same ``lax.cond`` firewall as
    :func:`_make_update_fn`. The segmented-overlap step computes the exchange
    inside its backward walk and lands here with the aggregated f32 gradient
    and the candidate comm_state in hand."""

    def apply_reduced(params, opt_state, agg_grads, cand_comm, comm_state,
                      skipped):
        def plain_update():
            g = agg_grads
            if clip_grad_norm is not None:
                g, _ = _optim.clip_grad_norm_(g, clip_grad_norm)
            new_params, new_opt_state = optimizer.update(g, opt_state, params)
            return new_params, new_opt_state, cand_comm

        if not guard:
            new_params, new_opt_state, new_comm = plain_update()
            return new_params, new_opt_state, new_comm, skipped
        # post-allreduce f32 gradient: the sum propagated any replica's
        # NaN/Inf everywhere, so this replica-local check IS the global
        # verdict — same contract as the barrier path.
        ok = guard_lib.tree_all_finite(agg_grads)
        return _firewall_gate(ok, plain_update, params, opt_state, comm_state,
                              skipped)

    return apply_reduced


def _make_segmented_vjp(model, criterion, axis_name, sync_buffers: str,
                        augment: Optional[Callable], segments):
    """The forward half of the segmented step: run the model one segment at a
    time, saving each segment's VJP closure instead of one whole-model
    ``value_and_grad``. Returns ``seg_vjp(state, x, y, w) ->
    (pullbacks, ct, model_state, loss, n)`` where ``ct`` is the loss
    cotangent w.r.t. the logits — the seed for the reversed backward walk.

    Parity contract: each segment applies ``model[i].apply(params[i],
    model_state[i], x, ctx.child(i))`` at the ABSOLUTE child index ``i`` —
    byte for byte the calls ``Sequential.apply`` makes — so the composed
    forward and the chained per-segment pullbacks execute the same
    primitives over the same operands as the barrier step's single VJP."""

    def seg_vjp(state: TrainState, x, y, w):
        aug_rng, dropout_rng = _split_step_rng(state, axis_name)
        if augment is not None:
            x = augment(aug_rng, x)
        ctx = Context(
            train=True, rng=dropout_rng, axis_name=axis_name, sample_weight=w
        )
        act = x
        pullbacks = []
        new_states = []
        for seg in segments:
            a, b = seg.layers
            s_seg = tuple(state.model_state[a:b])

            def seg_fwd(p, v, a=a, b=b, s_seg=s_seg):
                out = v
                states = []
                for j, i in enumerate(range(a, b)):
                    out, s = model[i].apply(p[j], s_seg[j], out, ctx.child(i))
                    states.append(s)
                return out, tuple(states)

            act, pull, st_seg = jax.vjp(
                seg_fwd, tuple(state.params[a:b]), act, has_aux=True
            )
            pullbacks.append(pull)
            new_states.extend(st_seg)
        # loss head: criterion value + logits cotangent in one VJP — the same
        # criterion backward the barrier step's whole-model grad begins with
        loss, ct = jax.value_and_grad(lambda lg: criterion(lg, y, w))(act)
        model_state = tuple(new_states)
        if axis_name is not None and sync_buffers == "broadcast":
            model_state = col.broadcast(model_state, root=0, axis_name=axis_name)
        elif axis_name is not None and sync_buffers == "pmean":
            model_state = col.pmean(model_state, axis_name)
        return pullbacks, ct, model_state, loss, jnp.sum(w)

    return seg_vjp


def _segmented_exchange(pullbacks, ct, residual, comm, segments, axis_name,
                        grad_of_seg):
    """The reversed backward walk WITH the eager per-segment exchange: pull
    segment K-1's gradient, issue its collective immediately, then pull
    segment K-2 — the collective has no data dependence on the earlier
    segments' compute, so it interleaves. ``grad_of_seg(k, dp_seg) ->
    f32 gradient subtree to exchange`` is the identity for the single-step
    path and the accumulated cycle-mean fold for grad accumulation.

    Returns ``(agg_grads, new_comm_state)`` — bitwise the barrier step's
    ``comm.reduce`` (or per-leaf pmean) outputs, reassembled from the
    per-segment slices in forward order."""
    n_seg = len(segments)
    red = [None] * n_seg
    res = [None] * n_seg
    for k in range(n_seg - 1, -1, -1):
        dp_seg, ct = pullbacks[k](ct)
        g_seg = grad_of_seg(k, dp_seg)
        seg = segments[k]
        if comm is not None and comm.compressed:
            lo, hi = seg.flat
            g_vec = _subtree_to_vec(g_seg, hi - lo)
            if comm.needs_residual:
                send = g_vec + jax.lax.slice(residual, (lo,), (hi,))
            else:
                send = g_vec
            summed, kept = comm.exchange_segment(send, seg, axis_name)
            red[k] = summed / comm.world
            if comm.needs_residual:
                res[k] = send - kept
        else:
            # hook "none": the segment's slice of THE DDP pmean — identical
            # leaves to the barrier col.pmean over the whole tree
            red[k] = col.pmean(g_seg, axis_name)
    if comm is not None and comm.compressed:
        agg_grads = _vec_to_tree(jnp.concatenate(red), comm.spec)
        new_comm = jnp.concatenate(res) if comm.needs_residual else residual
    else:
        layers = []
        for r in red:
            layers.extend(r)
        agg_grads = tuple(layers)
        new_comm = residual
    return agg_grads, new_comm


def _make_segmented_train_core(
    model,
    criterion,
    optimizer,
    axis_name,
    sync_buffers: str,
    clip_grad_norm: Optional[float],
    augment: Optional[Callable],
    comm,
    segments,
    guard: bool = False,
):
    """The segmented-overlap sibling of :func:`_make_train_core`: same
    ``core(state, x, y, w) -> (new_state, metrics)`` signature and bitwise the
    same arithmetic, with the gradient exchange issued per segment inside the
    backward walk instead of as one trailing block."""
    _validate_sync_buffers(model, axis_name, sync_buffers)
    if axis_name is None:
        raise ValueError(
            "comm_overlap needs the explicit per-replica step "
            "(mode='shard_map'): only there is the gradient collective an "
            "explicit op that can be issued per backward segment"
        )
    seg_vjp = _make_segmented_vjp(
        model, criterion, axis_name, sync_buffers, augment, segments
    )
    apply_reduced = _make_apply_reduced(optimizer, clip_grad_norm, guard)

    def core(state: TrainState, x, y, w):
        pullbacks, ct, model_state, loss, n = seg_vjp(state, x, y, w)
        agg_grads, cand_comm = _segmented_exchange(
            pullbacks, ct, state.comm_state, comm, segments, axis_name,
            lambda k, dp: dp,
        )
        new_params, new_opt_state, new_comm, new_skipped = apply_reduced(
            state.params, state.opt_state, agg_grads, cand_comm,
            state.comm_state, state.skipped_steps,
        )
        if guard:
            skipped_now = new_skipped["total"] != state.skipped_steps["total"]
            model_state = jax.tree_util.tree_map(
                lambda old, new: jnp.where(skipped_now, old, new),
                state.model_state, model_state,
            )
        metrics = {
            "loss_sum": (loss * n)[None],
            "n": n[None],
        }
        new_state = TrainState(
            params=new_params,
            model_state=model_state,
            opt_state=new_opt_state,
            step=state.step + 1,
            rng=state.rng,
            comm_state=new_comm,
            skipped_steps=new_skipped,
        )
        return new_state, metrics

    return core


def _make_eval_core(model, criterion, axis_name, transform: Optional[Callable]):
    def core(state: TrainState, x, y, w):
        if transform is not None:
            x = transform(x)
        ctx = Context(train=False, rng=None, axis_name=axis_name, sample_weight=w)
        logits, _ = model.apply(state.params, state.model_state, x, ctx)
        loss = criterion(logits, y, w)
        n = jnp.sum(w)
        predicted = jnp.argmax(logits, axis=-1)
        correct = jnp.sum((predicted == y) * w)
        return {
            "loss_sum": (loss * n)[None],
            "correct": correct[None],
            "n": n[None],
        }

    return core


def build_train_step(
    model,
    criterion,
    optimizer,
    mesh,
    mode: str = "shard_map",
    sync_buffers: str = "broadcast",
    clip_grad_norm: Optional[float] = None,
    augment: Optional[Callable] = None,
    remat: bool = False,
    wus_spec: Optional[FlatParamSpec] = None,
    state_spec=None,
    comm=None,
    guard: bool = False,
    hier: Optional[Tuple[str, str]] = None,
    segments=None,
):
    """Compile the DP train step over ``mesh``. Returns
    ``step(state, (x, y, w)) -> (new_state, metrics)`` with donated state.
    ``wus_spec``/``state_spec`` (from :func:`make_flat_param_spec` /
    :func:`sharded_state_spec`) switch on weight-update sharding. ``comm``
    (a :class:`tpuddp.parallel.comm.GradComm`) switches the gradient
    exchange to the bucketed compressed hook pipeline; an error-feedback
    hook needs a ``state_spec`` marking ``comm_state`` sharded
    (:func:`comm_state_spec` or :func:`sharded_state_spec` with ``comm=``).
    ``hier=(inner, outer)`` routes the exchange hierarchically over a
    factored mesh (see :func:`_make_update_fn`). ``guard=True`` arms the
    non-finite gradient firewall (state must carry ``skipped_steps``
    counters; see resilience/guard.py); ``False`` lowers to the identical
    program as before the guard existed. ``segments`` (a tuple of
    :class:`tpuddp.parallel.comm.CommSegment` from ``comm.make_segments``)
    selects the segmented-overlap step — mutually exclusive with
    ``wus_spec``/``hier`` and shard_map-only (DDP._resolve_overlap enforces
    the eligibility matrix and auto-falls back)."""
    _validate_segments(segments, mode, wus_spec, hier)
    if mode == "shard_map":
        axis = data_axes(mesh)
        st_spec = state_spec if state_spec is not None else P()
        if segments is not None:
            core = _make_segmented_train_core(
                model, criterion, optimizer, axis, sync_buffers,
                clip_grad_norm, augment, comm=comm, segments=segments,
                guard=guard,
            )
        else:
            core = _make_train_core(
                model, criterion, optimizer, axis, sync_buffers,
                clip_grad_norm, augment, remat, wus_spec=wus_spec, comm=comm,
                guard=guard, hier=hier,
            )
        fn = shard_map(
            core,
            mesh=mesh,
            in_specs=(st_spec, P(axis), P(axis), P(axis)),
            out_specs=(st_spec, {"loss_sum": P(axis), "n": P(axis)}),
            check_vma=False,
        )
        jitted = jax.jit(fn, donate_argnums=0)
    elif mode == "auto":
        core = _make_train_core(
            model, criterion, optimizer, None, sync_buffers,
            clip_grad_norm, augment, remat, wus_spec=wus_spec, comm=comm,
            guard=guard,
        )
        jitted = jax.jit(
            core,
            in_shardings=(replicated(mesh), data_sharded(mesh), data_sharded(mesh), data_sharded(mesh)),
            out_shardings=(replicated(mesh), replicated(mesh)),
            donate_argnums=0,
        )
    else:
        raise ValueError(f"unknown mode {mode!r}; one of 'shard_map', 'auto'")

    def step(state, batch):
        x, y, w = batch
        return jitted(state, x, y, w)

    # the underlying jit-wrapped callable, exposed for HLO inspection (the
    # overlap proof obligation: tests/bench lower the step and assert the
    # collectives interleave with backward compute instead of trailing)
    step.jitted = jitted
    return step


def build_train_scan_step(
    model,
    criterion,
    optimizer,
    mesh,
    mode: str = "shard_map",
    sync_buffers: str = "broadcast",
    clip_grad_norm: Optional[float] = None,
    augment: Optional[Callable] = None,
    remat: bool = False,
    wus_spec: Optional[FlatParamSpec] = None,
    state_spec=None,
    grad_accumulation: int = 1,
    comm=None,
    guard: bool = False,
    hier: Optional[Tuple[str, str]] = None,
    segments=None,
):
    """Multi-step variant: runs K train steps per jit call via ``lax.scan``.

    Takes batches stacked on a leading steps axis ``(K, batch, ...)`` and
    returns summed metrics. Semantically identical to K calls of the single
    step (same RNG fold per state.step, same metric totals) but amortizes
    per-dispatch host/runtime latency K-fold — on remote-tunneled or
    dispatch-bound runtimes this is the difference between RPC-bound and
    MXU-bound throughput. K is static per compilation (one cache entry per
    distinct K, so group epochs into fixed-size chunks).

    ``grad_accumulation=A > 1`` turns every A consecutive micro-batches into
    ONE optimizer update (effective-batch control, the native analog of the
    managed path's ``gradient_accumulation_steps`` — reference
    multi-GPU-training-torch.py:88's batch size knob): the scan is
    restructured as cycles of A micro-batches whose sample-weighted gradient
    sums accumulate in the carry; the cycle boundary pays ONE cross-replica
    exchange + clip + update on the n-weighted average — exactly the gradient
    of one step over the A micro-batches' concatenation (all-padding
    micro-batches contribute nothing, so tails can be padded to a static
    cycle length). K must be a multiple of A.
    """
    if mode == "shard_map":
        axis_name = data_axes(mesh)
        in_batch = P(None, axis_name)
        metric_spec = P(axis_name)
    elif mode == "auto":
        axis_name, in_batch = None, None
    else:
        raise ValueError(f"unknown mode {mode!r}; one of 'shard_map', 'auto'")

    accum = int(grad_accumulation)
    if accum < 1:
        raise ValueError(f"grad_accumulation must be >= 1, got {grad_accumulation!r}")
    _validate_sync_buffers(model, axis_name, sync_buffers)
    _validate_segments(segments, mode, wus_spec, hier)
    if wus_spec is not None and axis_name is None:
        raise ValueError(
            "weight_update_sharding needs the explicit per-replica step "
            "(mode='shard_map'): the reduce-scatter/all-gather exchange is "
            "expressed over its named data axis"
        )

    if accum == 1:
        if segments is not None:
            core = _make_segmented_train_core(
                model, criterion, optimizer, axis_name, sync_buffers,
                clip_grad_norm, augment, comm=comm, segments=segments,
                guard=guard,
            )
        else:
            core = _make_train_core(
                model, criterion, optimizer, axis_name, sync_buffers,
                clip_grad_norm, augment, remat, wus_spec=wus_spec, comm=comm,
                guard=guard, hier=hier,
            )

        def multi(state: TrainState, xs, ys, ws):
            def body(st, batch):
                x, y, w = batch
                st, m = core(st, x, y, w)
                return st, m

            state, stacked = jax.lax.scan(body, state, (xs, ys, ws))
            metrics = jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), stacked)
            return state, metrics
    else:
        grad_core = _make_grad_core(
            model, criterion, axis_name, sync_buffers, augment, remat
        )
        apply_update = _make_update_fn(
            optimizer, axis_name, clip_grad_norm, wus_spec, comm=comm,
            guard=guard, hier=hier,
        )
        if segments is not None:
            # grad-accum peel: the first A-1 micro-batches scan through the
            # barrier grad_core accumulating Σ n·g as before; the LAST micro
            # runs segmented, folding (gacc + n·g)/denom per segment during
            # its backward walk so the cycle's ONE exchange still overlaps
            # that backward. Bitwise: the fold is exactly the barrier's last
            # scan iteration + /denom, leaf for leaf.
            seg_vjp = _make_segmented_vjp(
                model, criterion, axis_name, sync_buffers, augment, segments
            )
            apply_reduced = _make_apply_reduced(
                optimizer, clip_grad_norm, guard
            )

        def multi(state: TrainState, xs, ys, ws):
            k = xs.shape[0]
            if k % accum != 0:
                raise ValueError(
                    f"scan length {k} is not a multiple of "
                    f"grad_accumulation={accum}; pad the chunk to a whole "
                    "number of accumulation cycles (training/loop.py does "
                    "this with all-padding micro-batches)"
                )
            cyc = (
                xs.reshape(k // accum, accum, *xs.shape[1:]),
                ys.reshape(k // accum, accum, *ys.shape[1:]),
                ws.reshape(k // accum, accum, *ws.shape[1:]),
            )

            def cycle(st, cyc_batch):
                zeros = jax.tree_util.tree_map(jnp.zeros_like, st.params)
                ms0 = st.model_state  # pre-cycle buffers for the guard revert

                def micro(carry, mb):
                    st, gacc, nacc = carry
                    x, y, w = mb
                    grads, model_state, loss, n = grad_core(st, x, y, w)
                    # n-weighted gradient sum: micro-batch i's local grad is
                    # the mean over its n_i live samples, so Σ n_i·g_i / Σ n_i
                    # is EXACTLY the mean gradient of the concatenated batch,
                    # padded/ragged micro-batches included
                    gacc = jax.tree_util.tree_map(
                        lambda a, g: a + n * g, gacc, grads
                    )
                    st = TrainState(
                        params=st.params,
                        model_state=model_state,
                        opt_state=st.opt_state,
                        step=st.step + 1,
                        rng=st.rng,
                        comm_state=st.comm_state,
                        skipped_steps=st.skipped_steps,
                    )
                    m = {"loss_sum": (loss * n)[None], "n": n[None]}
                    return (st, gacc, nacc + n), m

                if segments is None:
                    (st, gacc, nacc), stacked = jax.lax.scan(
                        micro, (st, zeros, jnp.zeros((), jnp.float32)), cyc_batch
                    )
                    # exact weighted mean even for fractional sample weights
                    # (guard only the all-padding nacc==0 case, like nn/loss.py)
                    denom = jnp.where(nacc == 0, 1.0, nacc)
                    g = jax.tree_util.tree_map(lambda a: a / denom, gacc)
                    # the firewall (guard=True) checks THIS aggregated
                    # cycle-mean gradient: one poisoned micro-batch skips the
                    # whole cycle's update, bitwise
                    new_params, new_opt_state, new_comm, new_skipped = apply_update(
                        st.params, st.opt_state, g, st.comm_state, st.skipped_steps
                    )
                else:
                    head = jax.tree_util.tree_map(
                        lambda arr: arr[: accum - 1], cyc_batch
                    )
                    (st, gacc, nacc), head_stacked = jax.lax.scan(
                        micro, (st, zeros, jnp.zeros((), jnp.float32)), head
                    )
                    x_l, y_l, w_l = jax.tree_util.tree_map(
                        lambda arr: arr[accum - 1], cyc_batch
                    )
                    pullbacks, ct, ms_l, loss_l, n_l = seg_vjp(st, x_l, y_l, w_l)
                    nacc = nacc + n_l
                    denom = jnp.where(nacc == 0, 1.0, nacc)

                    def grad_of_seg(k, dp):
                        lo, hi = segments[k].layers
                        return jax.tree_util.tree_map(
                            lambda acc, d: (acc + n_l * d) / denom,
                            gacc[lo:hi], dp,
                        )

                    agg, cand_comm = _segmented_exchange(
                        pullbacks, ct, st.comm_state, comm, segments,
                        axis_name, grad_of_seg,
                    )
                    st = TrainState(
                        params=st.params,
                        model_state=ms_l,
                        opt_state=st.opt_state,
                        step=st.step + 1,
                        rng=st.rng,
                        comm_state=st.comm_state,
                        skipped_steps=st.skipped_steps,
                    )
                    m_l = {"loss_sum": (loss_l * n_l)[None], "n": n_l[None]}
                    # stack the peeled micro back onto the head so the metric
                    # sum reduces over the SAME length-A array as the barrier
                    # cycle (identical reduction order, bitwise totals)
                    stacked = jax.tree_util.tree_map(
                        lambda h, last: jnp.concatenate([h, last[None]], axis=0),
                        head_stacked, m_l,
                    )
                    new_params, new_opt_state, new_comm, new_skipped = apply_reduced(
                        st.params, st.opt_state, agg, cand_comm,
                        st.comm_state, st.skipped_steps,
                    )
                model_state = st.model_state
                if guard:
                    # a skipped cycle also reverts the buffers the cycle's
                    # forwards (poisoned micro-batch included) accumulated —
                    # the cycle is the atomic update unit
                    skipped_now = new_skipped["total"] != st.skipped_steps["total"]
                    model_state = jax.tree_util.tree_map(
                        lambda old, new: jnp.where(skipped_now, old, new),
                        ms0, st.model_state,
                    )
                st = TrainState(
                    params=new_params,
                    model_state=model_state,
                    opt_state=new_opt_state,
                    step=st.step,
                    rng=st.rng,
                    comm_state=new_comm,
                    skipped_steps=new_skipped,
                )
                metrics = jax.tree_util.tree_map(
                    lambda a: jnp.sum(a, axis=0), stacked
                )
                return st, metrics

            state, stacked = jax.lax.scan(cycle, state, cyc)
            metrics = jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), stacked)
            return state, metrics

    if mode == "shard_map":
        st_spec = state_spec if state_spec is not None else P()
        fn = shard_map(
            multi,
            mesh=mesh,
            in_specs=(st_spec, in_batch, in_batch, in_batch),
            out_specs=(st_spec, {"loss_sum": metric_spec, "n": metric_spec}),
            check_vma=False,
        )
        jitted = jax.jit(fn, donate_argnums=0)
    else:
        rep, sh = replicated(mesh), NamedSharding(mesh, P(None, DATA_AXIS))
        jitted = jax.jit(
            multi,
            in_shardings=(rep, sh, sh, sh),
            out_shardings=(rep, rep),
            donate_argnums=0,
        )

    def step(state, stacked_batch):
        xs, ys, ws = stacked_batch
        return jitted(state, xs, ys, ws)

    step.jitted = jitted  # for HLO inspection (see build_train_step)
    return step


def stack_batches(batches):
    """Stack K host batches [(x, y, w), ...] into one (K, ...) super-batch for
    the scan step."""
    xs, ys, ws = zip(*batches)
    import numpy as np

    return np.stack(xs), np.stack(ys), np.stack(ws)


def build_eval_step(
    model,
    criterion,
    mesh,
    mode: str = "shard_map",
    transform: Optional[Callable] = None,
    state_spec=None,
):
    """Compile the DP eval step: ``eval_step(state, (x, y, w)) -> metrics``
    (per-replica partial sums in shard_map mode, global sums in auto mode).
    ``state_spec`` describes a weight-update-sharded TrainState (the eval
    core never reads the optimizer state, but the input placement must
    match)."""
    if mode == "shard_map":
        axis = data_axes(mesh)
        core = _make_eval_core(model, criterion, axis, transform)
        fn = shard_map(
            core,
            mesh=mesh,
            in_specs=(
                state_spec if state_spec is not None else P(),
                P(axis), P(axis), P(axis),
            ),
            out_specs={"loss_sum": P(axis), "correct": P(axis), "n": P(axis)},
            check_vma=False,
        )
        jitted = jax.jit(fn)
    elif mode == "auto":
        core = _make_eval_core(model, criterion, None, transform)
        jitted = jax.jit(
            core,
            in_shardings=(replicated(mesh), data_sharded(mesh), data_sharded(mesh), data_sharded(mesh)),
            out_shardings=replicated(mesh),
        )
    else:
        raise ValueError(f"unknown mode {mode!r}")

    def step(state, batch):
        x, y, w = batch
        return jitted(state, x, y, w)

    return step


def build_eval_scan_step(
    model,
    criterion,
    mesh,
    mode: str = "shard_map",
    transform: Optional[Callable] = None,
    state_spec=None,
):
    """Multi-batch eval variant: K eval batches per jit call via ``lax.scan``
    over a ``(K, batch, ...)`` stack, returning summed metrics — the eval-pass
    analog of :func:`build_train_scan_step` (without it the eval epoch is
    per-batch dispatch-bound, reference warm loop
    multi-GPU-training-torch.py:136-153)."""
    if mode == "shard_map":
        axis = data_axes(mesh)
        core = _make_eval_core(model, criterion, axis, transform)
    elif mode == "auto":
        core = _make_eval_core(model, criterion, None, transform)
    else:
        raise ValueError(f"unknown mode {mode!r}; one of 'shard_map', 'auto'")

    def multi(state: TrainState, xs, ys, ws):
        def body(carry, batch):
            return carry, core(state, *batch)

        _, stacked = jax.lax.scan(body, 0, (xs, ys, ws))
        return jax.tree_util.tree_map(lambda a: jnp.sum(a, axis=0), stacked)

    if mode == "shard_map":
        in_batch = P(None, axis)
        fn = shard_map(
            multi,
            mesh=mesh,
            in_specs=(
                state_spec if state_spec is not None else P(),
                in_batch, in_batch, in_batch,
            ),
            out_specs={
                "loss_sum": P(axis),
                "correct": P(axis),
                "n": P(axis),
            },
            check_vma=False,
        )
        jitted = jax.jit(fn)
    else:
        rep, sh = replicated(mesh), NamedSharding(mesh, P(None, DATA_AXIS))
        jitted = jax.jit(multi, in_shardings=(rep, sh, sh, sh), out_shardings=rep)

    def step(state, stacked_batch):
        xs, ys, ws = stacked_batch
        return jitted(state, xs, ys, ws)

    return step


def accumulate_metrics(acc, new):
    """On-device accumulation of per-step metric sums (fixes quirk Q5 — no
    ``loss.item()`` host sync per batch; dispatch stays async)."""
    if acc is None:
        return new
    return jax.tree_util.tree_map(jnp.add, acc, new)


_tree_sum_jit = jax.jit(
    lambda t: jax.tree_util.tree_map(jnp.sum, t)
)


def finalize_metrics(acc):
    """Epoch-end aggregation: ONE jitted cross-device sum over the whole
    metric tree — the analog of the reference's five ``dist.all_reduce`` calls
    (:198-204) — then one host fetch. ``acc`` may be any pytree of metric
    arrays (e.g. ``{"train": ..., "eval": ...}``); None subtrees are allowed
    and come back as empty dicts."""
    if acc is None:
        return {}
    summed = _tree_sum_jit(acc)
    return jax.tree_util.tree_map(float, jax.device_get(summed))
