"""Checkpointing — save/restore of arbitrary pytrees with the reference's
single-writer discipline, plus the resume path the reference lacks.

Reference contract (multi-GPU-training-torch.py:217-223; SURVEY.md §2b #18):
rank 0 saves ``ckpt_{epoch}`` every ``checkpoint_epoch`` epochs, then a
barrier so no reader races the writer. Divergences, deliberate and documented:

- the saved tree is the *unwrapped* state (quirk Q4: the reference saves the
  DDP-wrapped, ``module.``-prefixed state dict; the accelerate path saves
  unwrapped — tpuddp follows the accelerate/unwrapped convention);
- a load/resume path exists (the reference only documents loading,
  README.md:51-52).

Format: a single ``.npz`` holding flattened leaves keyed by their pytree
paths. PRNG key arrays are stored via ``jax.random.key_data`` and re-wrapped
on load. Loading requires a template ("like") pytree for the treedef — the
natural JAX analog of ``model.load_state_dict``.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

import jax
import ml_dtypes
import numpy as np

from tpuddp.parallel import collectives as col

_KEY_MARK = "__prngkey__"
_BF16_MARK = "__bf16__"  # npz can't serialize ml_dtypes natively (loads back
# as void16); bf16 leaves — e.g. Adam moments under optimizer_state_dtype —
# are stored as a uint16 bit view and re-viewed on load.


def _path_str(path) -> str:
    return jax.tree_util.keystr(path)


def save(path: str, tree: Any) -> str:
    """Serialize a pytree to ``path`` (.npz). Caller handles rank gating."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    payload = {}
    for p, leaf in flat:
        key = _path_str(p)
        arr = leaf
        if hasattr(arr, "dtype") and jax.dtypes.issubdtype(arr.dtype, jax.dtypes.prng_key):
            payload[_KEY_MARK + key] = np.asarray(jax.random.key_data(arr))
        elif hasattr(arr, "dtype") and arr.dtype == ml_dtypes.bfloat16:
            payload[_BF16_MARK + key] = np.asarray(arr).view(np.uint16)
        else:
            payload[key] = np.asarray(arr)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic publish, no torn checkpoints
    return path


def _check_leaf(path: str, key: str, stored: np.ndarray, template: Any) -> np.ndarray:
    """Shape/dtype validation against the template leaf — the analog of
    torch ``load_state_dict``'s size-mismatch error. A same-layout checkpoint
    with different widths (e.g. a 12-class head into a 10-class model) must
    fail loudly here, not train silently with wrong-width logits."""
    t_shape = tuple(np.shape(template))
    t_dtype = np.asarray(template).dtype if not hasattr(template, "dtype") else template.dtype
    if tuple(stored.shape) != t_shape:
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has shape {tuple(stored.shape)} "
            f"but the model expects {t_shape}"
        )
    if stored.dtype != t_dtype:
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has dtype {stored.dtype} but "
            f"the model expects {t_dtype} (if this is optimizer state, check "
            "training.optimizer_state_dtype matches the saved run)"
        )
    return stored


def load(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save`, using ``like`` for structure.
    Leaf shapes and dtypes are validated against ``like``; mismatches raise
    with the offending leaf named."""
    with np.load(path) as data:
        stored = dict(data.items())
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for p, template in flat:
        key = _path_str(p)
        if key in stored:
            leaves.append(_check_leaf(path, key, stored[key], template))
        elif _BF16_MARK + key in stored:
            arr = stored[_BF16_MARK + key].view(ml_dtypes.bfloat16)
            leaves.append(_check_leaf(path, key, arr, template))
        elif _KEY_MARK + key in stored:
            raw = stored[_KEY_MARK + key]
            if not (
                hasattr(template, "dtype")
                and jax.dtypes.issubdtype(template.dtype, jax.dtypes.prng_key)
            ):
                raise ValueError(
                    f"checkpoint {path}: leaf {key!r} holds a PRNG key but the "
                    "model expects an ordinary array"
                )
            t_raw_shape = tuple(np.shape(jax.random.key_data(template)))
            if tuple(raw.shape) != t_raw_shape:
                raise ValueError(
                    f"checkpoint {path}: PRNG key leaf {key!r} has key-data "
                    f"shape {tuple(raw.shape)} but the model expects "
                    f"{t_raw_shape}"
                )
            leaves.append(jax.random.wrap_key_data(raw))
        else:
            raise KeyError(f"checkpoint {path} is missing leaf {key!r}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def checkpoint_path(save_dir: str, epoch: int, prefix: str = "ckpt") -> str:
    """``{prefix}_{epoch}.npz`` — default naming parity with the reference's
    ``ckpt_{epoch}.pt`` (multi-GPU-training-torch.py:219-221); the managed
    full-state files use ``prefix="state"``."""
    return os.path.join(save_dir, f"{prefix}_{epoch}.npz")


def _gather_cross_host_shards(tree: Any) -> Any:
    """Materialize leaves that are sharded ACROSS hosts (weight-update-sharded
    optimizer moments: no single process holds the full vector) as host
    arrays. A collective — every process must call it, which is why it runs
    BEFORE the process-0 gating in :func:`save_on_main`. Replicated
    multi-host arrays are locally complete and need no exchange."""
    def g(leaf):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
            and not leaf.sharding.is_fully_replicated
        ):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(leaf, tiled=True)
        return leaf

    return jax.tree_util.tree_map(g, tree)


def save_on_main(
    save_dir: str, epoch: int, tree: Any, prefix: str = "ckpt"
) -> Optional[str]:
    """Process-0-only save + barrier — the reference's writer discipline
    (:217-223), with the cross-host shard gather (a collective) BEFORE the
    process-0 gate. Returns the path on process 0, None elsewhere. The
    managed full-state files use ``prefix="state"``."""
    if jax.process_count() > 1:
        tree = _gather_cross_host_shards(tree)
    path = None
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
        path = save(checkpoint_path(save_dir, epoch, prefix), tree)
    col.barrier("tpuddp_checkpoint")
    return path


def latest(save_dir: str, prefix: str = "ckpt") -> Optional[Tuple[str, int]]:
    """Most recent ``(path, epoch)`` in ``save_dir``, or None. The resume
    helper the reference lacks (SURVEY.md §3.4)."""
    if not os.path.isdir(save_dir):
        return None
    pat = re.compile(rf"^{re.escape(prefix)}_(\d+)\.npz$")
    best = None
    for name in os.listdir(save_dir):
        m = pat.match(name)
        if m:
            epoch = int(m.group(1))
            if best is None or epoch > best[1]:
                best = (os.path.join(save_dir, name), epoch)
    return best


def restore_latest(save_dir: str, like: Any, prefix: str = "ckpt") -> Tuple[Any, int]:
    """Load the newest checkpoint into ``like``'s structure. Returns
    ``(tree, next_epoch)``; ``(like, 0)`` when none exists."""
    found = latest(save_dir, prefix)
    if found is None:
        return like, 0
    path, epoch = found
    return load(path, like), epoch + 1
