"""Checkpointing — save/restore of arbitrary pytrees with the reference's
single-writer discipline, plus the resume path the reference lacks.

Reference contract (multi-GPU-training-torch.py:217-223; SURVEY.md §2b #18):
rank 0 saves ``ckpt_{epoch}`` every ``checkpoint_epoch`` epochs, then a
barrier so no reader races the writer. Divergences, deliberate and documented:

- the saved tree is the *unwrapped* state (quirk Q4: the reference saves the
  DDP-wrapped, ``module.``-prefixed state dict; the accelerate path saves
  unwrapped — tpuddp follows the accelerate/unwrapped convention);
- a load/resume path exists (the reference only documents loading,
  README.md:51-52).

Format: a single ``.npz`` holding flattened leaves keyed by their pytree
paths. PRNG key arrays are stored via ``jax.random.key_data`` and re-wrapped
on load. Loading requires a template ("like") pytree for the treedef — the
natural JAX analog of ``model.load_state_dict``.

Resilience (ISSUE 1): every save publishes a ``.sha256`` sidecar manifest;
``latest()`` verifies candidates newest-first and *skips* corrupt/truncated
files with a logged warning instead of crashing the resume path; a small
``__meta__*`` record inside the npz distinguishes end-of-epoch checkpoints
(``completed=1`` -> resume at epoch+1) from preemption-drain emergency saves
(``completed=0`` -> redo the interrupted epoch); ``keep_last`` pruning bounds
checkpoint disk on long runs.

Elastic resume (ISSUE 7): checkpoints written through ``save_on_main`` carry
a **format-v2 topology record** — world size, mesh axes/shape, and a per-leaf
shard tag for every world-size-DEPENDENT leaf (the weight-update-sharded flat
optimizer vectors, padded to a world multiple, and the bf16_ef per-replica
error-feedback residual). Replicated leaves are world-independent and carry
no tag. On ``load``/``restore_latest`` onto a *different* world size M (the
checkpoint's was N):

- untagged (replicated) leaves load unchanged — the broadcast is implicit;
- ``data_flat`` leaves (flat vectors zero-padded to a world multiple) are
  re-padded to the new world's length — exact, because the tail past the raw
  element count is zeros by construction;
- ``per_replica`` leaves (the ``(N * per,)`` bf16_ef residual) are
  redistributed **sum-preservingly** when M | N or N | M
  (:func:`tpuddp.parallel.comm.redistribute_residual`), and RESET to zero
  (with a typed ``comm_state_reset`` event handed to the caller's
  ``reshard_log``) when neither divides — the documented fallback.

Same-topology loads take the identical byte-for-byte path as before (shapes
match, no reshard). v1 checkpoints (no topology record) keep loading
unchanged on their original topology; loaded onto a DIFFERENT world size
their world-dependent leaves mismatch and raise :class:`TopologyMismatch`
pointing at the v2 elastic path instead of reshaping or mis-slicing.

2-D mesh (format v3, ISSUE 14): checkpoints written on a ``("data",
"model")`` mesh additionally record the **model width** and a per-leaf
``placement`` map (which mesh axes each sharded leaf's dimensions split
over). Parameter/moment leaves are stored as their FULL logical arrays (the
single-controller save gathers shards transparently), so they are
model-width-independent on disk — what is NOT width-independent is the
per-``(data, model)``-device error-feedback residual. ``load`` /
``restore_latest`` take the current ``model_size``; by default a
cross-model-width restore REFUSES with a typed :class:`TopologyMismatch`
instead of mis-slicing. With ``reshard_on_mismatch=True`` (the
``training.reshard_on_mismatch`` knob) the payload is first re-shaped
in-memory by :mod:`tpuddp.training.reshard` — the cross-topology reshaper
behind ``tpuddp_inspect reshard`` — and then loads on the target mesh; see
that module's doc for the exact/reset contract (README "2-D mesh"). A v2
file written on a 2-D mesh carries the mesh axes/shape, so the same rules
apply to it; a v1 file (no topology record) still refuses either way.
"""

from __future__ import annotations

import json
import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import ml_dtypes
import numpy as np

from tpuddp.parallel import collectives as col
from tpuddp.resilience import faults, integrity

logger = logging.getLogger("tpuddp")

FORMAT_VERSION = 4  # v2 = topology record present (elastic resume);
# v3 = the record additionally carries model_size + per-leaf mesh-axis
# placement tags (the 2-D ("data", "model") mesh — ISSUE 14). v2 files keep
# loading: readers key on record CONTENTS, and a v2 record written on a 2-D
# mesh already names its mesh axes/shape, so the cross-model-width refusal
# covers it too. v4 = the file MAY carry a ``__cursor__`` data-cursor record
# (epoch, step, sampler epoch-plan key, partial metric accumulator) written
# by step-granular snapshots — restore_latest resumes EXACTLY mid-epoch from
# it instead of redoing the interrupted epoch. Cursor-less v4 files are
# byte-compatible with v3; v3 readers never see the cursor (template
# iteration skips dunder entries, like the meta/topology records).

_KEY_MARK = "__prngkey__"
_BF16_MARK = "__bf16__"  # npz can't serialize ml_dtypes natively (loads back
# as void16); bf16 leaves — e.g. Adam moments under optimizer_state_dtype —
# are stored as a uint16 bit view and re-viewed on load.
_META_MARK = "__meta__"  # scalar bookkeeping (epoch, completed flag) stored
# alongside the leaves; load() iterates the template's leaves so meta keys are
# invisible to it, and read_meta() reads them without needing a template.
_TOPO_MARK = "__topology__"  # v2: one JSON record (world size, mesh axes, and
# per-leaf shard tags for world-size-dependent leaves) — the metadata the
# elastic reshard path needs; invisible to template iteration like the meta.
_CURSOR_MARK = "__cursor__"  # v4: one JSON record — the DATA CURSOR of a
# step-granular snapshot (epoch, step = real micro-batches applied, the
# sampler epoch-plan key, and the names of the partial-accumulator arrays
# stored under _CURSOR_ACC_MARK). Its presence marks a mid-epoch snapshot;
# restore_latest surfaces it so the driver replays ZERO batches.
_CURSOR_ACC_MARK = "__cursor_acc__"  # v4: the partial per-epoch metric
# accumulator (e.g. {loss_sum, n} device fold) at the snapshot step, one
# array per entry — seeding the resumed epoch's fold keeps the loss
# trajectory bitwise-equal to an uninterrupted run.


class TopologyMismatch(ValueError):
    """A checkpoint's world-size-dependent state cannot be fitted onto the
    current topology: either the file predates the v2 topology record (v1
    checkpoints have no resharding story) or the elastic reshard lacks the
    information it needs (e.g. the current world size)."""


def _path_str(path) -> str:
    return jax.tree_util.keystr(path)


# Leaf-path anchors for world-size-dependent state. Anchored to the
# TrainState fields / managed state-dict entries — a model parameter whose
# own name merely CONTAINS "comm_state" must not match.
_COMM_FLAT_KEYS = (".comm_state", "['comm_state']")  # the flat residual vector


def _is_opt_state_key(key: str) -> bool:
    return key.startswith(".opt_state") or key.startswith("['opt_state']")


def _is_world_dependent_key(key: str) -> bool:
    """Could this leaf's shape depend on the world size? (The flat bf16_ef
    residual and the weight-update-sharded flat optimizer vectors do; params,
    buffers, counters, and tree-shaped moments never do.)"""
    return key in _COMM_FLAT_KEYS or _is_opt_state_key(key)


def derive_topology(tree: Any, world_size: Optional[int] = None) -> Optional[dict]:
    """The v2 topology record for ``tree``: world size, mesh axes/shape, and
    a shard tag per world-size-dependent leaf. Derived from the leaves' live
    ``NamedSharding``s (the common case: a training state still on the mesh);
    ``world_size`` overrides/supplies the world when shardings are absent
    (host-array trees, multi-host states already gathered). Returns None when
    no world size is derivable — the save then carries no topology record
    and loads with v1 semantics."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    mesh_axes = mesh_shape = None
    world = int(world_size) if world_size else None

    def sharding_of(leaf):
        if isinstance(leaf, jax.Array):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and getattr(sh, "mesh", None) is not None:
                return sh
        return None

    for _p, leaf in flat:
        sh = sharding_of(leaf)
        if sh is not None:
            try:
                mesh = sh.mesh
                mesh_axes = [str(a) for a in mesh.axis_names]
                mesh_shape = [int(d) for d in np.shape(mesh.devices)]
                if world is None:
                    world = int(np.prod(mesh_shape))
            except Exception:  # AbstractMesh etc.: keep what we have
                pass
            break
    if world is None:
        return None
    model = 1
    if mesh_axes and mesh_shape and "model" in mesh_axes:
        model = int(mesh_shape[mesh_axes.index("model")])

    def spec_axes(sh):
        """JSON-able per-dimension mesh-axis placement of a NamedSharding's
        spec (tuple entries become lists) — the v3 leaf placement tag."""
        try:
            out = []
            for entry in tuple(sh.spec):
                if entry is None:
                    out.append(None)
                elif isinstance(entry, (tuple, list)):
                    out.append([str(a) for a in entry])
                else:
                    out.append(str(entry))
            return out
        except Exception:
            return None

    leaves: Dict[str, dict] = {}
    placement: Dict[str, list] = {}
    for p, leaf in flat:
        key = _path_str(p)
        sh = sharding_of(leaf)
        sharded = sh is not None and not sh.is_fully_replicated
        if sharded:
            # v3: every sharded leaf names the mesh axes each dimension
            # splits over — params/moments on the model axis included (they
            # are SAVED as full gathered arrays, so the tag is provenance
            # plus the refusal surface, not a reshape instruction)
            axes = spec_axes(sh)
            if axes is not None:
                placement[key] = axes
        if np.ndim(leaf) != 1:
            continue
        n = int(np.shape(leaf)[0])
        if key in _COMM_FLAT_KEYS:
            if sharded and n % world == 0:
                # shard_map EF residual: (world * per,) per-replica slices.
                # On a 2-D mesh the slices key by (data_index, model_index)
                # — "model" > 1 marks them NON-redistributable across any
                # width change (the typed-refusal path).
                leaves[key] = {
                    "kind": "per_replica", "world": world, "per": n // world,
                    "model": model,
                }
            else:
                # auto-mode bf16_ef: the replicated (total,) aggregate
                # residual — world-dependent only through its padding
                leaves[key] = {"kind": "data_flat"}
        elif _is_opt_state_key(key) and sharded and model == 1:
            # weight-update-sharded flat moment vector: (total,) padded to a
            # world multiple, sharded over the data axis — re-padded on load
            leaves[key] = {"kind": "data_flat"}
    return {
        "format": FORMAT_VERSION,
        "world_size": world,
        "model_size": model,
        "mesh_axes": mesh_axes,
        "mesh_shape": mesh_shape,
        "leaves": leaves,
        "placement": placement,
    }


def read_topology(path: str) -> Optional[dict]:
    """The v2/v3 topology record of a checkpoint (None for v1 files)."""
    with np.load(path) as data:
        if _TOPO_MARK not in data.files:
            return None
        return json.loads(str(np.asarray(data[_TOPO_MARK]).item()))


def topology_model_size(topo: Optional[dict]) -> int:
    """The model-axis width a checkpoint was written under: the explicit v3
    field, else derived from the v2 record's mesh axes (a v2 file written on
    a 2-D mesh already named them), else 1 — every 1-D data mesh IS the
    model=1 case."""
    if not topo:
        return 1
    if topo.get("model_size") is not None:
        return int(topo["model_size"])
    axes, shape = topo.get("mesh_axes"), topo.get("mesh_shape")
    if axes and shape and "model" in axes:
        return int(shape[list(axes).index("model")])
    return 1


def _check_model_width(path: str, topo: Optional[dict], model_size) -> None:
    """The cross-``model``-width refusal (ISSUE 14 satellite): a checkpoint
    written under one tensor-parallel width restored under another would
    mis-slice its per-device state (and a v1 file has no mesh record at
    all) — raise the typed mismatch instead. Same width passes; the data
    axis keeps its own elastic rules."""
    cur = 1 if model_size is None else int(model_size)
    if topo is None:
        if cur > 1:
            raise TopologyMismatch(
                f"checkpoint {path} predates the topology record (format v1) "
                f"and cannot be restored onto a model={cur} tensor-parallel "
                "mesh: it carries no shard provenance, so even the reshaper "
                "refuses it. Resume it on a pure-DP world (model=1) or "
                "re-save it through save_on_main (format v3) first."
            )
        return
    saved = topology_model_size(topo)
    if saved != cur:
        raise TopologyMismatch(
            f"checkpoint {path} was written on a model={saved} mesh but the "
            f"current run is model={cur}. Cross-topology restore is opt-in: "
            "set training.reshard_on_mismatch=true to reshard on load, or "
            "reshape the file offline with `tpuddp_inspect reshard "
            f"--to data=D,model={cur}` (README '2-D mesh' documents which "
            "reshapes are exact and which reset the comm residual)."
        )


def save(
    path: str,
    tree: Any,
    meta: Optional[Dict[str, int]] = None,
    topology: Optional[dict] = None,
    cursor: Optional[dict] = None,
    cursor_acc: Optional[Any] = None,
) -> str:
    """Serialize a pytree to ``path`` (.npz). Caller handles rank gating.
    ``meta``: optional dict of int scalars (e.g. epoch, completed) stored as
    ``__meta__*`` entries, readable via :func:`read_meta` without a template.
    ``topology``: the v2 elastic record (see :func:`derive_topology`) —
    stored as a ``__topology__`` JSON entry whose presence marks the file
    format v2; None writes a v1-compatible file (no resharding story).
    ``cursor``: the v4 data-cursor record of a step-granular snapshot
    (JSON-able dict; see :mod:`tpuddp.training.snapshot`), with
    ``cursor_acc`` the partial metric-accumulator pytree stored alongside it.
    A ``.sha256`` manifest sidecar is published after the data file so
    ``latest()`` can verify integrity before trusting a checkpoint.
    The publish is durable: the staged bytes are fsync'd before the atomic
    rename, so a host crash right after ``save`` returns cannot leave a
    checkpoint that is intact in the page cache but torn on disk."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    payload = {}
    for p, leaf in flat:
        key = _path_str(p)
        arr = leaf
        if hasattr(arr, "dtype") and jax.dtypes.issubdtype(arr.dtype, jax.dtypes.prng_key):
            payload[_KEY_MARK + key] = np.asarray(jax.random.key_data(arr))
        elif hasattr(arr, "dtype") and arr.dtype == ml_dtypes.bfloat16:
            payload[_BF16_MARK + key] = np.asarray(arr).view(np.uint16)
        else:
            payload[key] = np.asarray(arr)
    if topology is not None:
        # the record's presence IS the v2 marker (read_topology returns None
        # for v1 files); the meta scalars stay exactly the v1 set so
        # pre-elastic readers of read_meta() see an unchanged contract
        payload[_TOPO_MARK] = np.asarray(json.dumps(topology))
    if cursor is not None:
        acc_payload = {}
        if cursor_acc is not None:
            for p, leaf in jax.tree_util.tree_flatten_with_path(cursor_acc)[0]:
                k = _path_str(p)
                if hasattr(leaf, "dtype") and leaf.dtype == ml_dtypes.bfloat16:
                    acc_payload[_CURSOR_ACC_MARK + _BF16_MARK + k] = (
                        np.asarray(leaf).view(np.uint16)
                    )
                else:
                    acc_payload[_CURSOR_ACC_MARK + k] = np.asarray(leaf)
        record = dict(cursor)
        record["acc_keys"] = sorted(acc_payload)
        payload[_CURSOR_MARK] = np.asarray(json.dumps(record, sort_keys=True))
        for k in sorted(acc_payload):
            payload[k] = acc_payload[k]
    for k, v in (meta or {}).items():
        payload[_META_MARK + k] = np.asarray(int(v), dtype=np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic publish, no torn checkpoints
    integrity.write_manifest(path)
    return path


def read_cursor(path: str) -> Optional[dict]:
    """The v4 data-cursor record of a step-granular snapshot, with its
    partial accumulator re-inflated under ``"acc"`` (a flat dict keyed by
    the original pytree paths). None for epoch-granular / pre-v4 files."""
    with np.load(path) as data:
        if _CURSOR_MARK not in data.files:
            return None
        record = json.loads(str(np.asarray(data[_CURSOR_MARK]).item()))
        acc: Dict[str, np.ndarray] = {}
        for k in record.pop("acc_keys", []):
            if k not in data.files:
                continue
            name = k[len(_CURSOR_ACC_MARK):]
            if name.startswith(_BF16_MARK):
                acc[name[len(_BF16_MARK):]] = np.asarray(data[k]).view(
                    ml_dtypes.bfloat16
                )
            else:
                acc[name] = np.asarray(data[k])
        record["acc"] = acc or None
        return record


def read_meta(path: str) -> Dict[str, int]:
    """The ``__meta__*`` scalars of a checkpoint (empty for pre-meta files)."""
    out: Dict[str, int] = {}
    with np.load(path) as data:
        for k in data.files:
            if k.startswith(_META_MARK):
                out[k[len(_META_MARK) :]] = int(data[k])
    return out


def _check_dtype(path: str, key: str, stored: np.ndarray, template: Any) -> None:
    t_dtype = np.asarray(template).dtype if not hasattr(template, "dtype") else template.dtype
    if stored.dtype != t_dtype:
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has dtype {stored.dtype} but "
            f"the model expects {t_dtype} (if this is optimizer state, check "
            "training.optimizer_state_dtype matches the saved run)"
        )


def _refit_flat(path: str, key: str, stored: np.ndarray, t_shape) -> np.ndarray:
    """Re-pad a flat world-padded vector (WUS moments, the auto-mode bf16_ef
    residual) to the current world's length. Exact: both lengths are the raw
    element count padded up to a world multiple, and every element past the
    raw count is zero by construction — so truncating a longer vector may
    only drop zeros (verified), and growing one appends zeros."""
    n_new = int(t_shape[0])
    n_old = int(stored.shape[0])
    if n_new < n_old and np.any(stored[n_new:]):
        raise TopologyMismatch(
            f"checkpoint {path}: flat leaf {key!r} has {n_old} elements but "
            f"the current topology expects {n_new}, and the tail past "
            f"{n_new} is non-zero — this is not world-multiple padding (was "
            "the model changed, not just the world size?)"
        )
    out = np.zeros((n_new,), stored.dtype)
    out[: min(n_old, n_new)] = stored[: min(n_old, n_new)]
    return out


def _fit_leaf(
    path: str,
    key: str,
    stored: np.ndarray,
    template: Any,
    topo: Optional[dict],
    world_size: Optional[int],
    actions: Optional[List[dict]],
) -> np.ndarray:
    """Shape/dtype validation against the template leaf — the analog of
    torch ``load_state_dict``'s size-mismatch error — PLUS the elastic
    reshard path: a v2-tagged world-size-dependent leaf whose shape differs
    from the template's is re-fitted to the current topology instead of
    failing. A same-layout checkpoint with different widths (e.g. a 12-class
    head into a 10-class model) must still fail loudly here, not train
    silently with wrong-width logits."""
    t_shape = tuple(np.shape(template))
    if tuple(stored.shape) == t_shape:
        _check_dtype(path, key, stored, template)
        return stored  # same topology: byte-identical fast path
    info = ((topo or {}).get("leaves") or {}).get(key)
    if info is None:
        if topo is None and _is_world_dependent_key(key) and stored.ndim == 1 and len(t_shape) == 1:
            raise TopologyMismatch(
                f"checkpoint {path}: world-size-dependent leaf {key!r} has "
                f"shape {tuple(stored.shape)} but the current topology "
                f"expects {t_shape}. This checkpoint predates the format-v2 "
                "topology record and cannot be resharded onto a different "
                "world size — resume it on the topology that wrote it, or "
                "re-save it through save_on_main (elastic v2) first."
            )
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has shape {tuple(stored.shape)} "
            f"but the model expects {t_shape}"
        )
    _check_dtype(path, key, stored, template)
    from_world = int((topo or {}).get("world_size") or 0) or None
    if info["kind"] == "data_flat":
        out = _refit_flat(path, key, stored, t_shape)
        if actions is not None:
            actions.append({
                "leaf": key, "action": "repadded",
                "from_shape": list(stored.shape), "to_shape": list(t_shape),
            })
        return out
    if info["kind"] == "per_replica":
        if int(info.get("model", 1) or 1) > 1:
            # a 2-D-mesh residual keys by (data_index, model_index); the
            # row-group redistribution below assumes pure data rows. The
            # reshaper (tpuddp.training.reshard) redistributes it per model
            # column — this in-loader path refuses so the opt-in stays the
            # single entry point for cross-topology fitting.
            raise TopologyMismatch(
                f"checkpoint {path}: per-replica leaf {key!r} was written on "
                f"a model={info['model']} mesh under a different data width; "
                "set training.reshard_on_mismatch=true (or reshape offline "
                "with `tpuddp_inspect reshard`) to redistribute it, or "
                "resume on the same (data, model) grid"
            )
        if world_size is None:
            raise TopologyMismatch(
                f"checkpoint {path}: per-replica leaf {key!r} (saved on a "
                f"{info['world']}-replica world) needs the CURRENT world "
                "size to redistribute; pass world_size= to load/"
                "restore_latest (the epoch drivers do)"
            )
        from tpuddp.parallel.comm import redistribute_residual

        n_from, per_from = int(info["world"]), int(info["per"])
        if stored.shape[0] != n_from * per_from:
            raise TopologyMismatch(
                f"checkpoint {path}: per-replica leaf {key!r} has "
                f"{stored.shape[0]} elements but its topology record says "
                f"{n_from} x {per_from}"
            )
        if int(t_shape[0]) % int(world_size) != 0:
            raise TopologyMismatch(
                f"checkpoint {path}: per-replica leaf {key!r} target length "
                f"{t_shape[0]} is not a multiple of world_size={world_size}"
            )
        per_to = int(t_shape[0]) // int(world_size)
        mat = stored.reshape(n_from, per_from)
        # column re-pad first (the per-replica vector is itself world-padded)
        if per_from != per_to:
            cols = np.zeros((n_from, per_to), stored.dtype)
            keep = min(per_from, per_to)
            if per_from > per_to and np.any(mat[:, per_to:]):
                raise TopologyMismatch(
                    f"checkpoint {path}: per-replica leaf {key!r} carries "
                    f"non-zero data past the current per-replica length "
                    f"{per_to} — not world-multiple padding"
                )
            cols[:, :keep] = mat[:, :keep]
            mat = cols
        new_mat, action = redistribute_residual(mat, int(world_size))
        if actions is not None:
            actions.append({
                "leaf": key, "action": action,
                "from_world": n_from, "to_world": int(world_size),
            })
        if action == "reset":
            logger.warning(
                "checkpoint %s: per-replica leaf %r cannot be redistributed "
                "sum-preservingly from world %d to %d (no divisor relation); "
                "residual RESET to zero",
                path, key, n_from, world_size,
            )
        return new_mat.reshape(-1)
    raise TopologyMismatch(
        f"checkpoint {path}: leaf {key!r} has unknown shard tag {info!r}"
    )


def load_with_topology(
    path: str,
    like: Any,
    world_size: Optional[int] = None,
    reshard_actions: Optional[List[dict]] = None,
    model_size: Optional[int] = None,
    reshard_on_mismatch: bool = False,
) -> Tuple[Any, Optional[dict]]:
    """:func:`load` plus the file's parsed topology record (None for v1) —
    one file open for callers that need both (restore_latest, the managed
    load_state). ``model_size`` is the CURRENT tensor-parallel width (None =
    1, every pre-2-D caller); a width mismatch against the file's record is
    a typed :class:`TopologyMismatch` BEFORE any leaf is touched — unless
    ``reshard_on_mismatch`` (the ``training.reshard_on_mismatch`` knob)
    opts into the cross-topology reshaper, which re-shapes the payload
    in-memory onto the current ``(data, model)`` mesh first. Template
    validation still runs on the resharded payload, so genuinely
    incompatible trees (wrong head width, wrong dtype) keep failing loudly."""
    with np.load(path) as data:
        stored = dict(data.items())
    topo = None
    if _TOPO_MARK in stored:
        topo = json.loads(str(np.asarray(stored[_TOPO_MARK]).item()))
    cur_model = 1 if model_size is None else int(model_size)
    file_topo = topo  # the record as WRITTEN — what reshard events report
    if reshard_on_mismatch and topo is not None and world_size:
        saved_model = topology_model_size(topo)
        saved_world = int(topo.get("world_size") or 0)
        # model-width changes always need the reshaper; at a FIXED model>1
        # width a data-width change does too (the in-loader elastic path
        # only redistributes pure-DP residuals). model=1 world changes keep
        # the pre-existing in-loader elastic path — byte-identical behavior
        # for every pure-DP caller.
        if saved_model != cur_model or (
            cur_model > 1 and saved_world and saved_world != int(world_size)
        ):
            from tpuddp.training import reshard as reshard_lib

            stored, topo, racts = reshard_lib.reshard_arrays(
                stored,
                data=int(world_size) // cur_model,
                model=cur_model,
                path=path,
            )
            logger.warning(
                "elastic reshard: checkpoint %s re-shaped in-memory onto "
                "(data=%d, model=%d) before load (%d leaf action(s))",
                path, int(world_size) // cur_model, cur_model, len(racts),
            )
            if reshard_actions is not None:
                reshard_actions.extend(racts)
    _check_model_width(path, topo, model_size)
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for p, template in flat:
        key = _path_str(p)
        if key in stored:
            leaves.append(_fit_leaf(
                path, key, stored[key], template, topo, world_size,
                reshard_actions,
            ))
        elif _BF16_MARK + key in stored:
            arr = stored[_BF16_MARK + key].view(ml_dtypes.bfloat16)
            leaves.append(_fit_leaf(
                path, key, arr, template, topo, world_size, reshard_actions
            ))
        elif _KEY_MARK + key in stored:
            raw = stored[_KEY_MARK + key]
            if not (
                hasattr(template, "dtype")
                and jax.dtypes.issubdtype(template.dtype, jax.dtypes.prng_key)
            ):
                raise ValueError(
                    f"checkpoint {path}: leaf {key!r} holds a PRNG key but the "
                    "model expects an ordinary array"
                )
            t_raw_shape = tuple(np.shape(jax.random.key_data(template)))
            if tuple(raw.shape) != t_raw_shape:
                raise ValueError(
                    f"checkpoint {path}: PRNG key leaf {key!r} has key-data "
                    f"shape {tuple(raw.shape)} but the model expects "
                    f"{t_raw_shape}"
                )
            leaves.append(jax.random.wrap_key_data(raw))
        elif (
            key == ".comm_state"
            or key.startswith("['comm_state']")
            or key.startswith(".skipped_steps")
            or key.startswith("['skipped_steps']")
        ):
            # Anchored to the TrainState fields / managed state-dict entries —
            # a model parameter whose own name merely contains "comm_state"
            # must still hit the missing-leaf error below.
            # Forward-compat: a checkpoint written before the gradient-comm
            # hook (comm_hook="none" saves no residual leaf) or before the
            # numerical guard (guard off saves no skip counters) loads into
            # the newer template by keeping the template's zero
            # initialization — the exact state a fresh run of that
            # configuration starts from, so resume is correct, just logged.
            # A cross-model-width reshard DROPS the residual deliberately
            # (slices key by model shard); its topology record says so, and
            # the log names the reset instead of claiming the file is old.
            dropped = key in ((topo or {}).get("resharded") or {}).get(
                "dropped", ()
            )
            if dropped:
                logger.warning(
                    "checkpoint %s: leaf %r was reset by a cross-topology "
                    "reshard (model-width change); it restarts at its zero "
                    "initialization",
                    path, key,
                )
            else:
                logger.warning(
                    "checkpoint %s predates %s state: leaf %r starts at "
                    "its zero initialization",
                    path,
                    "guard" if "skipped_steps" in key else "comm_hook",
                    key,
                )
            leaves.append(template)
        else:
            raise KeyError(f"checkpoint {path} is missing leaf {key!r}")
    return jax.tree_util.tree_unflatten(treedef, leaves), file_topo


def load(
    path: str,
    like: Any,
    world_size: Optional[int] = None,
    reshard_actions: Optional[List[dict]] = None,
    model_size: Optional[int] = None,
    reshard_on_mismatch: bool = False,
) -> Any:
    """Restore a pytree saved by :func:`save`, using ``like`` for structure.
    Leaf shapes and dtypes are validated against ``like``; mismatches raise
    with the offending leaf named.

    Elastic resume: when the file carries a v2 topology record and a
    world-size-dependent leaf's shape differs from the template's, the leaf
    is resharded onto the current topology (see the module doc) instead of
    failing. ``world_size`` is the CURRENT world (needed to redistribute
    per-replica leaves); ``model_size`` the current tensor-parallel width
    (cross-width restores refuse typed unless ``reshard_on_mismatch`` opts
    into the cross-topology reshaper); ``reshard_actions`` (a
    caller-supplied list) is appended with one dict per resharded leaf."""
    return load_with_topology(
        path, like, world_size, reshard_actions, model_size=model_size,
        reshard_on_mismatch=reshard_on_mismatch,
    )[0]


def build_reshard_events(
    path: str,
    epoch: int,
    topo: Optional[dict],
    world_size: Optional[int],
    actions: List[dict],
    model_size: Optional[int] = None,
) -> List[dict]:
    """The typed event dicts an elastic restore should land in
    history.jsonl: one ``topology_change`` summary (worlds, model widths,
    resharded leaves, what happened to the residual) plus one
    ``comm_state_reset`` per residual that had to reset. Empty when the
    restore was same-topology. ONE implementation for every driver — the
    native epoch driver, the guard-rollback restore, and the managed
    load_state all record identically."""
    from_world = (topo or {}).get("world_size")
    from_model = topology_model_size(topo) if topo else None
    to_model = None if model_size is None else int(model_size)
    if not (actions or (from_world and world_size and from_world != world_size)):
        return []
    events = [{
        "event": "topology_change",
        "from_world": from_world,
        "to_world": world_size,
        "from_model": from_model,
        "to_model": to_model,
        "checkpoint": os.path.basename(path),
        "checkpoint_epoch": epoch,
        "resharded_leaves": [a["leaf"] for a in actions],
        "residual": next(
            (a["action"] for a in actions if a.get("from_world")), None
        ),
    }]
    for a in actions:
        if a.get("action") == "reset":
            events.append({
                "event": "comm_state_reset",
                "leaf": a["leaf"],
                "from_world": a["from_world"],
                "to_world": a["to_world"],
                "reason": a.get("reason")
                or "no divisor relation between world sizes; "
                "error-feedback residual reset to zero",
            })
    logger.warning(
        "elastic resume: checkpoint %s written on world %s restored onto "
        "world %s (%d leaf/leaves resharded)",
        path, from_world, world_size, len(actions),
    )
    return events


def checkpoint_path(save_dir: str, epoch: int, prefix: str = "ckpt") -> str:
    """``{prefix}_{epoch}.npz`` — default naming parity with the reference's
    ``ckpt_{epoch}.pt`` (multi-GPU-training-torch.py:219-221); the managed
    full-state files use ``prefix="state"``."""
    return os.path.join(save_dir, f"{prefix}_{epoch}.npz")


def step_checkpoint_path(
    save_dir: str, epoch: int, step: int, prefix: str = "ckpt"
) -> str:
    """``{prefix}_{epoch}_s{step}.npz`` — a STEP-granular snapshot taken
    mid-epoch (``step`` real micro-batches of ``epoch`` applied). The suffix
    is invisible to the pre-v4 ``{prefix}_{epoch}.npz`` listing regex, so
    old readers simply never see step files."""
    return os.path.join(save_dir, f"{prefix}_{epoch}_s{step}.npz")


def peer_checkpoint_dirs(save_dir: str) -> List[str]:
    """The peer-redundant spill directories reachable from ``save_dir``:
    every ``ring_*`` subdirectory of ``<heartbeat_dir>/peer_ckpt``. Peer
    redundancy rides the heartbeat channel's directory (the one filesystem
    location every process of a multi-process job can already reach), each
    process spilling its ring neighbor's snapshot bytes there — so the loss
    of any single host's local checkpoint directory still yields a full
    restore. Empty when no peer spills exist."""
    from tpuddp.resilience import watchdog

    hb = watchdog.heartbeat_dir(save_dir)
    if not hb:
        return []
    root = os.path.join(hb, "peer_ckpt")
    if not os.path.isdir(root):
        return []
    return sorted(
        os.path.join(root, d)
        for d in os.listdir(root)
        if d.startswith("ring_") and os.path.isdir(os.path.join(root, d))
    )


def _gather_cross_host_shards(tree: Any) -> Any:
    """Materialize leaves that are sharded ACROSS hosts (weight-update-sharded
    optimizer moments: no single process holds the full vector) as host
    arrays. A collective — every process must call it, which is why it runs
    BEFORE the process-0 gating in :func:`save_on_main`. Replicated
    multi-host arrays are locally complete and need no exchange."""
    def g(leaf):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
            and not leaf.sharding.is_fully_replicated
        ):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(leaf, tiled=True)
        return leaf

    return jax.tree_util.tree_map(g, tree)


def save_on_main(
    save_dir: str,
    epoch: int,
    tree: Any,
    prefix: str = "ckpt",
    completed: bool = True,
    keep_last: Optional[int] = None,
    world_size: Optional[int] = None,
    step: Optional[int] = None,
    cursor: Optional[dict] = None,
    cursor_acc: Optional[Any] = None,
) -> Optional[str]:
    """Process-0-only save + barrier — the reference's writer discipline
    (:217-223), with the cross-host shard gather (a collective) BEFORE the
    process-0 gate. Returns the path on process 0, None elsewhere. The
    managed full-state files use ``prefix="state"``.

    ``completed=False`` marks a preemption-drain emergency save (resume redoes
    ``epoch`` instead of starting at ``epoch + 1``); ``keep_last=K`` prunes all
    but the K newest epochs after a successful save. The v2 topology record
    is derived from the tree's live shardings BEFORE the cross-host gather
    (which flattens sharded leaves to host arrays); ``world_size`` supplies
    the world when no sharding is inspectable.

    ``step`` (with an optional v4 ``cursor``/``cursor_acc``) writes a
    STEP-granular mid-epoch file ``{prefix}_{epoch}_s{step}.npz`` instead —
    a resumable-at-step snapshot (always ``completed=0``); ``restore_latest``
    surfaces its cursor so the driver replays zero batches."""
    topology = derive_topology(tree, world_size)
    if jax.process_count() > 1:
        tree = _gather_cross_host_shards(tree)
    path = None
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
        if step is None:
            target = checkpoint_path(save_dir, epoch, prefix)
            meta = {"epoch": epoch, "completed": int(completed)}
        else:
            target = step_checkpoint_path(save_dir, epoch, step, prefix)
            meta = {"epoch": epoch, "completed": 0, "step": int(step)}
        path = save(
            target,
            tree,
            meta=meta,
            topology=topology,
            cursor=cursor,
            cursor_acc=cursor_acc,
        )
        # chaos hook: corrupt@ckpt_N garbles the just-published file (stale
        # manifest included), which latest() must then detect and skip
        name = os.path.basename(target)[: -len(".npz")]
        faults.maybe_fire("ckpt", name=name, path=path)
        if keep_last is not None:
            prune_checkpoints(save_dir, keep_last, prefix)
    col.barrier("tpuddp_checkpoint")
    return path


def _family_key(epoch: int, step: Optional[int]) -> Tuple[int, int, int]:
    """Total order over mixed step/epoch checkpoint families: newest first
    by ``(epoch, family, step)``. A FULL-epoch file ``{prefix}_{e}.npz``
    ranks newer than every step snapshot ``{prefix}_{e}_s*.npz`` of the same
    epoch — any epoch-family write of epoch e (end-of-epoch save or a
    preempt drain) happens after the last step snapshot of e."""
    return (int(epoch), 1 if step is None else 0, 0 if step is None else int(step))


def _all_checkpoints(
    save_dir: str, prefix: str = "ckpt"
) -> List[Tuple[str, int, Optional[int]]]:
    """All ``(path, epoch, step)`` matches, newest first (``step`` is None
    for epoch-granular files; ordering per :func:`_family_key`)."""
    if not os.path.isdir(save_dir):
        return []
    pat = re.compile(rf"^{re.escape(prefix)}_(\d+)(?:_s(\d+))?\.npz$")
    found = []
    for name in os.listdir(save_dir):
        m = pat.match(name)
        if m:
            step = int(m.group(2)) if m.group(2) is not None else None
            found.append((os.path.join(save_dir, name), int(m.group(1)), step))
    found.sort(key=lambda t: _family_key(t[1], t[2]), reverse=True)
    return found


def latest(save_dir: str, prefix: str = "ckpt") -> Optional[Tuple[str, int]]:
    """Most recent *intact* ``(path, epoch)`` in ``save_dir``, or None. The
    resume helper the reference lacks (SURVEY.md §3.4). Candidates that fail
    integrity verification (manifest mismatch, truncation, a writer killed
    mid-``save``) are skipped with a warning in favor of the next-newest good
    one — a corrupt newest checkpoint must not take down the resume path.
    Step snapshots participate in the ordering (see :func:`_family_key`);
    use :func:`read_cursor` on the returned path to see whether it is one."""
    for path, epoch, _step in _all_checkpoints(save_dir, prefix):
        if integrity.verify_file(path):
            return path, epoch
        logger.warning(
            "checkpoint %s failed integrity verification (corrupt or "
            "truncated); skipping it and falling back to the next-newest",
            path,
        )
    return None


def _latest_any(
    save_dir: str, prefix: str = "ckpt", include_peers: bool = True
) -> Optional[Tuple[str, int, Optional[int], str]]:
    """The freshest *intact* checkpoint across {local, peer, epoch-family}:
    ``(path, epoch, step, provenance)``. Candidates from ``save_dir`` carry
    provenance ``"local"``; candidates from the peer-redundant spill dirs
    (see :func:`peer_checkpoint_dirs`) carry ``"peer:ring_<i>"``. Equal
    freshness prefers local. Corrupt candidates are skipped with a warning —
    that skip is exactly what the peer copies exist for."""
    candidates = []
    for path, epoch, step in _all_checkpoints(save_dir, prefix):
        candidates.append((_family_key(epoch, step), 0, path, epoch, step, "local"))
    if include_peers:
        for pd in peer_checkpoint_dirs(save_dir):
            prov = f"peer:{os.path.basename(pd)}"
            for path, epoch, step in _all_checkpoints(pd, prefix):
                candidates.append(
                    (_family_key(epoch, step), 1, path, epoch, step, prov)
                )
    candidates.sort(key=lambda c: (c[0], -c[1]), reverse=True)
    for _key, _rank, path, epoch, step, prov in candidates:
        if integrity.verify_file(path):
            return path, epoch, step, prov
        logger.warning(
            "checkpoint %s (%s) failed integrity verification (corrupt or "
            "truncated); skipping it and falling back to the next-newest "
            "intact candidate across {local, peer, epoch-family}",
            path, prov,
        )
    return None


def sweep_stale_tmp(save_dir: str, prefix: str = "ckpt") -> int:
    """Delete orphaned ``{prefix}_*.npz.tmp`` / ``.sha256.tmp`` staging
    files. ``save()`` publishes atomically via ``os.replace``, so a writer
    killed mid-``np.savez`` (preemption, chaos kill) leaks its ``.tmp``
    forever — never a torn checkpoint, but unbounded junk on long chaotic
    runs, and a confusing artifact next to the real files. Swept at the two
    natural janitor points (``restore_latest`` before picking a candidate,
    ``prune_checkpoints`` after a save) and counted by ``tpuddp_inspect
    ckpt``'s directory integrity report. Returns the number removed."""
    if not os.path.isdir(save_dir):
        return 0
    pat = re.compile(
        rf"^{re.escape(prefix)}_\d+(_s\d+)?\.npz(\.sha256)?\.tmp$"
    )
    removed = 0
    for name in os.listdir(save_dir):
        if not pat.match(name):
            continue
        try:
            os.remove(os.path.join(save_dir, name))
            removed += 1
        except FileNotFoundError:
            pass
    if removed:
        logger.warning(
            "swept %d stale checkpoint tmp file(s) from %s (writer killed "
            "mid-save; the atomic publish means no torn checkpoints, only "
            "orphaned staging files)",
            removed, save_dir,
        )
    return removed


def prune_checkpoints(save_dir: str, keep_last: int, prefix: str = "ckpt") -> int:
    """Delete all but the ``keep_last`` newest ``{prefix}_*.npz`` (and their
    manifests), plus any stale ``.tmp`` staging orphans. Returns the number
    of checkpoints removed.

    Ordering is by ``(epoch, step)`` across MIXED step/epoch families (see
    :func:`_family_key`) — a burst of step snapshots must age out by recency,
    not by name family. One hard floor: the newest INTACT full-epoch
    checkpoint is never collected, even when ``keep_last`` step snapshots
    outrank it — it is the only epoch-granular fallback left if every newer
    step snapshot turns out corrupt, and step snapshots of a partially
    applied epoch are useless to pre-v4 tooling."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    sweep_stale_tmp(save_dir, prefix)
    all_ckpts = _all_checkpoints(save_dir, prefix)
    keep = {path for path, _e, _s in all_ckpts[:keep_last]}
    for path, _epoch, step in all_ckpts:
        if step is None and integrity.verify_file(path):
            keep.add(path)  # newest intact full-epoch file: never collected
            break
    removed = 0
    for path, _epoch, _step in all_ckpts:
        if path in keep:
            continue
        for p in (path, integrity.manifest_path(path)):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        removed += 1
        logger.info("pruned old checkpoint %s (keep_last=%d)", path, keep_last)
    return removed


def restore_latest(
    save_dir: str,
    like: Any,
    prefix: str = "ckpt",
    world_size: Optional[int] = None,
    reshard_log: Optional[List[dict]] = None,
    model_size: Optional[int] = None,
    reshard_on_mismatch: bool = False,
    cursor_out: Optional[List[dict]] = None,
) -> Tuple[Any, int]:
    """Load the newest intact checkpoint into ``like``'s structure. Returns
    ``(tree, next_epoch)``; ``(like, 0)`` when none exists. An emergency save
    (``completed=0`` meta, written during a preemption drain) yields its own
    epoch as ``next_epoch`` so the interrupted epoch is redone from the saved
    mid-epoch state; end-of-epoch saves yield ``epoch + 1``.

    Candidate selection spans {local, peer, epoch-family}: step-granular v4
    snapshots and peer-redundant spill copies (see
    :func:`peer_checkpoint_dirs`) compete with local epoch files on
    ``(epoch, step)`` freshness, freshest-INTACT wins, and the provenance of
    the pick is logged. A v4 step snapshot yields its cursor's epoch as
    ``next_epoch`` and appends the cursor (plus ``path``/``provenance``) to
    ``cursor_out`` — the driver then resumes EXACTLY at the recorded step,
    replaying zero batches, instead of redoing the epoch.

    Elastic resume: ``world_size`` is the CURRENT world; a v2 checkpoint
    written on a different world is resharded onto it (see :func:`load`).
    ``model_size`` is the current tensor-parallel width — a checkpoint
    written under a DIFFERENT model width raises the typed
    :class:`TopologyMismatch` unless ``reshard_on_mismatch`` opts into the
    cross-topology reshaper (see :func:`load_with_topology`).
    ``reshard_log`` (a caller-supplied list)
    receives ready-to-write typed event dicts — one ``topology_change``
    summary naming the worlds and the resharded leaves, plus one
    ``comm_state_reset`` per residual that had to reset (M∤N) — so the
    epoch driver can land them as event rows in history.jsonl."""
    sweep_stale_tmp(save_dir, prefix)
    found = _latest_any(save_dir, prefix)
    if found is None:
        return like, 0
    path, epoch, step, provenance = found
    if provenance != "local" or step is not None:
        logger.warning(
            "restore_latest: picked %s (epoch=%d, %s, provenance=%s) as the "
            "freshest intact candidate across {local, peer, epoch-family}",
            path, epoch,
            "full-epoch" if step is None else f"step={step}",
            provenance,
        )
    actions: List[dict] = []
    tree, topo = load_with_topology(
        path, like, world_size=world_size, reshard_actions=actions,
        model_size=model_size, reshard_on_mismatch=reshard_on_mismatch,
    )
    if reshard_log is not None:
        reshard_log.extend(
            build_reshard_events(
                path, epoch, topo, world_size, actions, model_size=model_size
            )
        )
    cursor = read_cursor(path)
    if cursor is not None:
        if actions:
            # a cross-topology reshard changes the data order (the sampler
            # plan keys on the world size), so the step cursor no longer
            # addresses the same batches — surface it, but poison the plan
            # key so the driver falls back to redoing the epoch from the
            # restored mid-epoch state instead of skipping wrong batches
            cursor = dict(cursor)
            cursor["plan_key"] = None
        if cursor_out is not None:
            entry = dict(cursor)
            entry["path"] = path
            entry["provenance"] = provenance
            cursor_out.append(entry)
        logger.warning(
            "resuming from STEP snapshot %s (epoch %d, step %s, "
            "provenance=%s); the interrupted epoch continues at the recorded "
            "step — zero batches replayed",
            path, int(cursor.get("epoch", epoch)), cursor.get("step"),
            provenance,
        )
        return tree, int(cursor.get("epoch", epoch))
    meta = read_meta(path)
    if not meta.get("completed", 1):
        logger.warning(
            "resuming from EMERGENCY checkpoint %s (preempted during epoch "
            "%d); that epoch restarts from the saved mid-epoch state",
            path,
            epoch,
        )
        return tree, epoch
    return tree, epoch + 1
