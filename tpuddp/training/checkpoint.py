"""Checkpointing — save/restore of arbitrary pytrees with the reference's
single-writer discipline, plus the resume path the reference lacks.

Reference contract (multi-GPU-training-torch.py:217-223; SURVEY.md §2b #18):
rank 0 saves ``ckpt_{epoch}`` every ``checkpoint_epoch`` epochs, then a
barrier so no reader races the writer. Divergences, deliberate and documented:

- the saved tree is the *unwrapped* state (quirk Q4: the reference saves the
  DDP-wrapped, ``module.``-prefixed state dict; the accelerate path saves
  unwrapped — tpuddp follows the accelerate/unwrapped convention);
- a load/resume path exists (the reference only documents loading,
  README.md:51-52).

Format: a single ``.npz`` holding flattened leaves keyed by their pytree
paths. PRNG key arrays are stored via ``jax.random.key_data`` and re-wrapped
on load. Loading requires a template ("like") pytree for the treedef — the
natural JAX analog of ``model.load_state_dict``.

Resilience (ISSUE 1): every save publishes a ``.sha256`` sidecar manifest;
``latest()`` verifies candidates newest-first and *skips* corrupt/truncated
files with a logged warning instead of crashing the resume path; a small
``__meta__*`` record inside the npz distinguishes end-of-epoch checkpoints
(``completed=1`` -> resume at epoch+1) from preemption-drain emergency saves
(``completed=0`` -> redo the interrupted epoch); ``keep_last`` pruning bounds
checkpoint disk on long runs.
"""

from __future__ import annotations

import logging
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import jax
import ml_dtypes
import numpy as np

from tpuddp.parallel import collectives as col
from tpuddp.resilience import faults, integrity

logger = logging.getLogger("tpuddp")

_KEY_MARK = "__prngkey__"
_BF16_MARK = "__bf16__"  # npz can't serialize ml_dtypes natively (loads back
# as void16); bf16 leaves — e.g. Adam moments under optimizer_state_dtype —
# are stored as a uint16 bit view and re-viewed on load.
_META_MARK = "__meta__"  # scalar bookkeeping (epoch, completed flag) stored
# alongside the leaves; load() iterates the template's leaves so meta keys are
# invisible to it, and read_meta() reads them without needing a template.


def _path_str(path) -> str:
    return jax.tree_util.keystr(path)


def save(path: str, tree: Any, meta: Optional[Dict[str, int]] = None) -> str:
    """Serialize a pytree to ``path`` (.npz). Caller handles rank gating.
    ``meta``: optional dict of int scalars (e.g. epoch, completed) stored as
    ``__meta__*`` entries, readable via :func:`read_meta` without a template.
    A ``.sha256`` manifest sidecar is published after the data file so
    ``latest()`` can verify integrity before trusting a checkpoint."""
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    payload = {}
    for p, leaf in flat:
        key = _path_str(p)
        arr = leaf
        if hasattr(arr, "dtype") and jax.dtypes.issubdtype(arr.dtype, jax.dtypes.prng_key):
            payload[_KEY_MARK + key] = np.asarray(jax.random.key_data(arr))
        elif hasattr(arr, "dtype") and arr.dtype == ml_dtypes.bfloat16:
            payload[_BF16_MARK + key] = np.asarray(arr).view(np.uint16)
        else:
            payload[key] = np.asarray(arr)
    for k, v in (meta or {}).items():
        payload[_META_MARK + k] = np.asarray(int(v), dtype=np.int64)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **payload)
    os.replace(tmp, path)  # atomic publish, no torn checkpoints
    integrity.write_manifest(path)
    return path


def read_meta(path: str) -> Dict[str, int]:
    """The ``__meta__*`` scalars of a checkpoint (empty for pre-meta files)."""
    out: Dict[str, int] = {}
    with np.load(path) as data:
        for k in data.files:
            if k.startswith(_META_MARK):
                out[k[len(_META_MARK) :]] = int(data[k])
    return out


def _check_leaf(path: str, key: str, stored: np.ndarray, template: Any) -> np.ndarray:
    """Shape/dtype validation against the template leaf — the analog of
    torch ``load_state_dict``'s size-mismatch error. A same-layout checkpoint
    with different widths (e.g. a 12-class head into a 10-class model) must
    fail loudly here, not train silently with wrong-width logits."""
    t_shape = tuple(np.shape(template))
    t_dtype = np.asarray(template).dtype if not hasattr(template, "dtype") else template.dtype
    if tuple(stored.shape) != t_shape:
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has shape {tuple(stored.shape)} "
            f"but the model expects {t_shape}"
        )
    if stored.dtype != t_dtype:
        raise ValueError(
            f"checkpoint {path}: leaf {key!r} has dtype {stored.dtype} but "
            f"the model expects {t_dtype} (if this is optimizer state, check "
            "training.optimizer_state_dtype matches the saved run)"
        )
    return stored


def load(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save`, using ``like`` for structure.
    Leaf shapes and dtypes are validated against ``like``; mismatches raise
    with the offending leaf named."""
    with np.load(path) as data:
        stored = dict(data.items())
    flat, treedef = jax.tree_util.tree_flatten_with_path(like)
    leaves = []
    for p, template in flat:
        key = _path_str(p)
        if key in stored:
            leaves.append(_check_leaf(path, key, stored[key], template))
        elif _BF16_MARK + key in stored:
            arr = stored[_BF16_MARK + key].view(ml_dtypes.bfloat16)
            leaves.append(_check_leaf(path, key, arr, template))
        elif _KEY_MARK + key in stored:
            raw = stored[_KEY_MARK + key]
            if not (
                hasattr(template, "dtype")
                and jax.dtypes.issubdtype(template.dtype, jax.dtypes.prng_key)
            ):
                raise ValueError(
                    f"checkpoint {path}: leaf {key!r} holds a PRNG key but the "
                    "model expects an ordinary array"
                )
            t_raw_shape = tuple(np.shape(jax.random.key_data(template)))
            if tuple(raw.shape) != t_raw_shape:
                raise ValueError(
                    f"checkpoint {path}: PRNG key leaf {key!r} has key-data "
                    f"shape {tuple(raw.shape)} but the model expects "
                    f"{t_raw_shape}"
                )
            leaves.append(jax.random.wrap_key_data(raw))
        elif (
            key == ".comm_state"
            or key.startswith("['comm_state']")
            or key.startswith(".skipped_steps")
            or key.startswith("['skipped_steps']")
        ):
            # Anchored to the TrainState fields / managed state-dict entries —
            # a model parameter whose own name merely contains "comm_state"
            # must still hit the missing-leaf error below.
            # Forward-compat: a checkpoint written before the gradient-comm
            # hook (comm_hook="none" saves no residual leaf) or before the
            # numerical guard (guard off saves no skip counters) loads into
            # the newer template by keeping the template's zero
            # initialization — the exact state a fresh run of that
            # configuration starts from, so resume is correct, just logged.
            logger.warning(
                "checkpoint %s predates %s state: leaf %r starts at "
                "its zero initialization",
                path,
                "guard" if "skipped_steps" in key else "comm_hook",
                key,
            )
            leaves.append(template)
        else:
            raise KeyError(f"checkpoint {path} is missing leaf {key!r}")
    return jax.tree_util.tree_unflatten(treedef, leaves)


def checkpoint_path(save_dir: str, epoch: int, prefix: str = "ckpt") -> str:
    """``{prefix}_{epoch}.npz`` — default naming parity with the reference's
    ``ckpt_{epoch}.pt`` (multi-GPU-training-torch.py:219-221); the managed
    full-state files use ``prefix="state"``."""
    return os.path.join(save_dir, f"{prefix}_{epoch}.npz")


def _gather_cross_host_shards(tree: Any) -> Any:
    """Materialize leaves that are sharded ACROSS hosts (weight-update-sharded
    optimizer moments: no single process holds the full vector) as host
    arrays. A collective — every process must call it, which is why it runs
    BEFORE the process-0 gating in :func:`save_on_main`. Replicated
    multi-host arrays are locally complete and need no exchange."""
    def g(leaf):
        if (
            isinstance(leaf, jax.Array)
            and not leaf.is_fully_addressable
            and not leaf.sharding.is_fully_replicated
        ):
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(leaf, tiled=True)
        return leaf

    return jax.tree_util.tree_map(g, tree)


def save_on_main(
    save_dir: str,
    epoch: int,
    tree: Any,
    prefix: str = "ckpt",
    completed: bool = True,
    keep_last: Optional[int] = None,
) -> Optional[str]:
    """Process-0-only save + barrier — the reference's writer discipline
    (:217-223), with the cross-host shard gather (a collective) BEFORE the
    process-0 gate. Returns the path on process 0, None elsewhere. The
    managed full-state files use ``prefix="state"``.

    ``completed=False`` marks a preemption-drain emergency save (resume redoes
    ``epoch`` instead of starting at ``epoch + 1``); ``keep_last=K`` prunes all
    but the K newest epochs after a successful save."""
    if jax.process_count() > 1:
        tree = _gather_cross_host_shards(tree)
    path = None
    if jax.process_index() == 0:
        os.makedirs(save_dir, exist_ok=True)
        path = save(
            checkpoint_path(save_dir, epoch, prefix),
            tree,
            meta={"epoch": epoch, "completed": int(completed)},
        )
        # chaos hook: corrupt@ckpt_N garbles the just-published file (stale
        # manifest included), which latest() must then detect and skip
        faults.maybe_fire("ckpt", name=f"{prefix}_{epoch}", path=path)
        if keep_last is not None:
            prune_checkpoints(save_dir, keep_last, prefix)
    col.barrier("tpuddp_checkpoint")
    return path


def _all_checkpoints(save_dir: str, prefix: str = "ckpt") -> List[Tuple[str, int]]:
    """All ``(path, epoch)`` matches, newest epoch first."""
    if not os.path.isdir(save_dir):
        return []
    pat = re.compile(rf"^{re.escape(prefix)}_(\d+)\.npz$")
    found = []
    for name in os.listdir(save_dir):
        m = pat.match(name)
        if m:
            found.append((os.path.join(save_dir, name), int(m.group(1))))
    found.sort(key=lambda t: t[1], reverse=True)
    return found


def latest(save_dir: str, prefix: str = "ckpt") -> Optional[Tuple[str, int]]:
    """Most recent *intact* ``(path, epoch)`` in ``save_dir``, or None. The
    resume helper the reference lacks (SURVEY.md §3.4). Candidates that fail
    integrity verification (manifest mismatch, truncation, a writer killed
    mid-``save``) are skipped with a warning in favor of the next-newest good
    one — a corrupt newest checkpoint must not take down the resume path."""
    for path, epoch in _all_checkpoints(save_dir, prefix):
        if integrity.verify_file(path):
            return path, epoch
        logger.warning(
            "checkpoint %s failed integrity verification (corrupt or "
            "truncated); skipping it and falling back to the next-newest",
            path,
        )
    return None


def prune_checkpoints(save_dir: str, keep_last: int, prefix: str = "ckpt") -> int:
    """Delete all but the ``keep_last`` newest ``{prefix}_*.npz`` (and their
    manifests). Returns the number of checkpoints removed."""
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    removed = 0
    for path, _epoch in _all_checkpoints(save_dir, prefix)[keep_last:]:
        for p in (path, integrity.manifest_path(path)):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass
        removed += 1
        logger.info("pruned old checkpoint %s (keep_last=%d)", path, keep_last)
    return removed


def restore_latest(save_dir: str, like: Any, prefix: str = "ckpt") -> Tuple[Any, int]:
    """Load the newest intact checkpoint into ``like``'s structure. Returns
    ``(tree, next_epoch)``; ``(like, 0)`` when none exists. An emergency save
    (``completed=0`` meta, written during a preemption drain) yields its own
    epoch as ``next_epoch`` so the interrupted epoch is redone from the saved
    mid-epoch state; end-of-epoch saves yield ``epoch + 1``."""
    found = latest(save_dir, prefix)
    if found is None:
        return like, 0
    path, epoch = found
    tree = load(path, like)
    meta = read_meta(path)
    if not meta.get("completed", 1):
        logger.warning(
            "resuming from EMERGENCY checkpoint %s (preempted during epoch "
            "%d); that epoch restarts from the saved mid-epoch state",
            path,
            epoch,
        )
        return tree, epoch
    return tree, epoch + 1
