"""Async step-granular checkpointing: device snapshots between dispatches,
a background writer, and the v4 data cursor for EXACT mid-epoch resume.

Every recovery path in the stack — preempt drain, guard rollback, elastic
resume, mesh reshard — used to bottom out on synchronous epoch-granular
``save_on_main``: a fault at step N of a long epoch lost the whole epoch,
and the save itself stalled the async pipeline for the full
serialize+fsync. This module removes both costs:

**Step-boundary device snapshot.** :meth:`SnapshotEngine.maybe` runs in the
dispatch loop between step dispatches. It folds the pipeline's pending
metric readbacks device-side (no host sync), takes a cheap on-device copy
of the ``TrainState`` + partial accumulator (``jnp.copy`` per leaf — an
async device-to-device dispatch that survives the donation of the original
buffers by the next step), and hands the copy to a bounded queue. The
staged queue never drains and the host never blocks: when the queue is
full the snapshot is SKIPPED (counted, not waited for). The step loop pays
only the enqueue — the snapshot span + ``host_stall`` accounting prove it.

**Background writer.** A daemon thread dequeues snapshots and serializes
them through the exact same :func:`tpuddp.training.checkpoint.save` path a
synchronous save takes — tmp + fsync + atomic rename + ``.sha256``
manifest — so an async snapshot of step N is byte-identical on disk to a
synchronous save of the same step (proven by test). Writer statistics
(snapshots written, queue-full skips, write seconds, bytes) land in a
``.writer.json`` sidecar next to each snapshot — deliberately OUTSIDE the
snapshot payload, which must stay mode-independent for byte identity.

**The v4 data cursor.** Each snapshot records ``(epoch, step, sampler
epoch-plan key)`` plus the partial metric accumulator in the checkpoint's
``__cursor__`` record. ``restore_latest`` surfaces it; the driver then
recomputes the plan key for the restored epoch (:func:`epoch_plan_key` —
a fingerprint of everything that determines the epoch's batch order) and,
on a match, resumes the epoch AT the recorded step via
:class:`EpochTailLoader` (random access through ``make_batch_plan`` — zero
batches replayed) with the accumulator fold seeded from the cursor. The
resumed loss trajectory is bitwise-equal to an uninterrupted same-seed
run. A plan-key mismatch (e.g. an elastic world resize changed the batch
order) falls back to the pre-v4 contract: redo the epoch from the restored
mid-epoch state.

**Peer-redundant placement.** With ``peer_redundancy`` on, each writing
process additionally spills its ring neighbor's snapshot bytes (payload +
manifest) under ``<heartbeat_dir>/peer_ckpt/ring_<i>`` — the heartbeat
channel's directory, the one filesystem location every process already
shares. ``restore_latest`` considers peer spills alongside local files,
freshest-intact wins, and logs the provenance — so losing any single
host's checkpoint directory still yields a full restore.

Config block (``training.snapshot``, unknown keys refused)::

    snapshot:
      every_steps: 50        # snapshot cadence in real micro-batches; 0=off
      async: true            # background writer (false = inline, for tests)
      inflight: 2            # bounded writer queue depth; full => skip
      peer_redundancy: false # spill ring-neighbor copies via heartbeat dir
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import re
import shutil
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp.observability import trace as trace_lib
from tpuddp.resilience import faults, integrity
from tpuddp.training import checkpoint as ckpt

logger = logging.getLogger("tpuddp")

SNAPSHOT_DEFAULTS: Dict[str, Any] = {
    "every_steps": 50,
    "async": True,
    "inflight": 2,
    "peer_redundancy": False,
}


@dataclass(frozen=True)
class SnapshotConfig:
    """Resolved ``training.snapshot`` block. ``every_steps == 0`` means the
    engine is off (the default: ``snapshot: null``). The config KEY is
    ``async`` (a Python keyword, hence the field name)."""

    every_steps: int = 0
    async_writes: bool = True
    inflight: int = 2
    peer_redundancy: bool = False

    @property
    def enabled(self) -> bool:
        return self.every_steps > 0

    def as_dict(self) -> Dict[str, Any]:
        return {
            "every_steps": self.every_steps,
            "async": self.async_writes,
            "inflight": self.inflight,
            "peer_redundancy": self.peer_redundancy,
        }


OFF = SnapshotConfig()


def resolve_snapshot(block) -> SnapshotConfig:
    """``training.snapshot`` -> :class:`SnapshotConfig`. None/False = off;
    True = all defaults; a mapping merges over :data:`SNAPSHOT_DEFAULTS`
    with unknown-key refusal (the config contract every block follows)."""
    if isinstance(block, SnapshotConfig):
        return block
    if block is None or block is False:
        return OFF
    if block is True:
        block = {}
    if not isinstance(block, dict):
        raise ValueError(
            "training.snapshot must be a mapping (or true/false), got "
            f"{type(block).__name__}"
        )
    from tpuddp.config import _merge_refusing_unknown

    cfg = _merge_refusing_unknown(SNAPSHOT_DEFAULTS, block, "training.snapshot")
    every = int(cfg["every_steps"])
    if every < 0:
        raise ValueError(
            f"training.snapshot.every_steps must be >= 0, got {every}"
        )
    inflight = int(cfg["inflight"])
    if inflight < 1:
        raise ValueError(
            f"training.snapshot.inflight must be >= 1, got {inflight}"
        )
    return SnapshotConfig(
        every_steps=every,
        async_writes=bool(cfg["async"]),
        inflight=inflight,
        peer_redundancy=bool(cfg["peer_redundancy"]),
    )


# ---------------------------------------------------------------- cursor --


def epoch_plan_key(loader, epoch: int) -> str:
    """Fingerprint of everything that determines ``loader``'s batch order
    for ``epoch``: loader class, length, batch size, seed, shuffle, world
    layout, and the epoch itself. Two runs with equal keys fetch identical
    batches at identical steps (``make_batch_plan`` random access is a pure
    function of exactly these), so a v4 cursor whose recorded key matches
    the restored run's recomputed key can skip the applied prefix without
    replaying or re-fetching a single batch. An elastic world resize, a
    different seed, or a different dataset all change the key — the driver
    then falls back to redoing the epoch."""
    inner = loader
    hops = 0
    while hops < 4:  # Prefetch/Tail/test delegating wrappers
        nxt = inner.__dict__.get("loader", inner.__dict__.get("inner"))
        if nxt is None:
            break
        inner = nxt
        hops += 1
    fields: Dict[str, Any] = {
        "loader": type(inner).__name__,
        "n_batches": len(loader),
        "batch_size": getattr(inner, "batch_size", None),
        "seed": getattr(inner, "seed", None),
        "shuffle": getattr(inner, "shuffle", None),
        "drop_last": getattr(inner, "drop_last", None),
        "world_size": getattr(inner, "world_size", None),
        "epoch": int(epoch),
    }
    local_ranks = getattr(inner, "local_ranks", None)
    if local_ranks is not None:
        fields["local_ranks"] = [int(r) for r in local_ranks]
    samplers = getattr(inner, "samplers", None)
    if samplers:
        s0 = samplers[0]
        fields["seed"] = getattr(s0, "seed", fields["seed"])
        fields["shuffle"] = getattr(s0, "shuffle", fields["shuffle"])
    canon = json.dumps(fields, sort_keys=True, default=str)
    return hashlib.sha256(canon.encode("utf-8")).hexdigest()[:16]


_KEYSTR_RE = re.compile(r"^\['([^']*)'\]$")


def acc_from_cursor(cursor: Optional[dict]) -> Optional[Dict[str, np.ndarray]]:
    """The cursor's partial accumulator as a plain dict keyed by the
    original metric names (``read_cursor`` returns pytree-path keys like
    ``['loss_sum']``). None when the cursor carries no accumulator."""
    acc = (cursor or {}).get("acc") or None
    if not acc:
        return None
    out: Dict[str, np.ndarray] = {}
    for k, v in acc.items():
        m = _KEYSTR_RE.match(k)
        out[m.group(1) if m else k] = v
    return out


class EpochTailLoader:
    """A view of ``loader`` starting at batch ``start`` — the resumed
    epoch's remaining batches, fetched by RANDOM ACCESS through
    ``make_batch_plan`` so the applied prefix is never assembled (zero
    batches replayed). Falls back to iterate-and-discard only for loaders
    without a plan. Everything else forwards to the underlying loader."""

    def __init__(self, loader, start: int):
        self.loader = loader
        self.start = int(start)

    def __len__(self) -> int:
        return max(0, len(self.loader) - self.start)

    def __iter__(self):
        plan = getattr(self.loader, "make_batch_plan", None)
        if plan is not None:
            steps, fetch = plan()
            for s in range(self.start, steps):
                yield fetch(s)
            return
        it = iter(self.loader)
        for _ in range(self.start):
            try:
                next(it)
            except StopIteration:
                return
        yield from it

    def __getattr__(self, name):
        return getattr(self.loader, name)


# ---------------------------------------------------------------- engine --


class _Job:
    __slots__ = ("state", "acc", "topology", "epoch", "step", "plan_key")

    def __init__(self, state, acc, topology, epoch, step, plan_key):
        self.state = state
        self.acc = acc
        self.topology = topology
        self.epoch = int(epoch)
        self.step = int(step)
        self.plan_key = plan_key


_STOP = object()


def writer_stats_path(path: str) -> str:
    """The writer-statistics sidecar of a snapshot. A separate file, NOT an
    entry in the npz: the payload must stay byte-identical between async
    and sync writers, and 'how busy was the writer' is exactly the kind of
    mode-dependent fact that would break that."""
    return path + ".writer.json"


def read_writer_stats(path: str) -> Optional[dict]:
    """The ``.writer.json`` sidecar of snapshot ``path`` (None if absent)."""
    try:
        with open(writer_stats_path(path), "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class SnapshotEngine:
    """The async step-granular checkpoint engine (module doc). One per
    training run; construct with the resolved config, call
    :meth:`begin_epoch` per epoch, :meth:`maybe` from the dispatch loop,
    :meth:`flush`/:meth:`final_snapshot` from the preempt drain, and
    :meth:`close` on the way out."""

    def __init__(
        self,
        save_dir: str,
        cfg: SnapshotConfig,
        *,
        prefix: str = "ckpt",
        world_size: Optional[int] = None,
        keep_last: Optional[int] = None,
        tracer=None,
        flight=None,
    ):
        self.save_dir = save_dir
        self.cfg = cfg
        self.prefix = prefix
        self.world_size = world_size
        self.keep_last = keep_last
        self.tracer = tracer if tracer is not None else trace_lib.NULL_TRACER
        self.flight = flight
        self.trace_parent = None  # the current epoch span (loop sets it)
        self.stats: Dict[str, Any] = {
            "snapshots": 0,
            "skipped_queue_full": 0,
            "flushes": 0,
            "write_s": 0.0,
            "bytes": 0,
            "last_epoch": None,
            "last_step": None,
            "last_path": None,
        }
        self._disarmed: Optional[str] = None
        self._next_due = cfg.every_steps
        self._lock = threading.Lock()
        self._errors: List[str] = []
        self._queue: Optional[queue.Queue] = (
            queue.Queue(maxsize=cfg.inflight) if cfg.async_writes else None
        )
        self._thread: Optional[threading.Thread] = None
        self._closed = False
        # process 0 owns the write (the single-writer checkpoint
        # discipline); other processes keep a live engine but never enqueue
        self._is_writer = jax.process_index() == 0

    # ------------------------------------------------------------ public --

    def describe(self) -> Dict[str, Any]:
        """The run_meta (schema v11) snapshot-provenance block."""
        out = self.cfg.as_dict()
        out["prefix"] = self.prefix
        if self._disarmed:
            out["disarmed"] = self._disarmed
        return out

    def begin_epoch(self, epoch: int, start_step: int = 0) -> None:
        """Reset the cadence for ``epoch`` (a resumed epoch passes the
        cursor step so the next snapshot lands one full cadence later)."""
        self._next_due = int(start_step) + self.cfg.every_steps

    def maybe(self, state, *, epoch: int, step: int, plan_key, drain=None) -> bool:
        """Snapshot ``state`` at ``(epoch, step)`` if the cadence is due.
        NEVER blocks the step loop: a full writer queue skips (counted in
        ``skipped_queue_full``) rather than waits. Returns True when a
        snapshot was taken (async: enqueued)."""
        if (
            self._disarmed
            or not self.cfg.enabled
            or not self._is_writer
            or step < self._next_due
        ):
            return False
        if self._queue is not None and self._queue.full():
            self.stats["skipped_queue_full"] += 1
            return False
        if not self._addressable(state):
            return False
        span = self.tracer.start_span(
            "snapshot", trace_lib.KIND_ACTION, parent=self.trace_parent,
            attrs={"epoch": int(epoch), "step": int(step),
                   "mode": "async" if self.cfg.async_writes else "sync"},
        )
        # partial accumulator: fold the pipeline's pending readbacks
        # device-side (no host sync) so the accumulator matches the state
        acc = drain.drain() if drain is not None else None
        # on-device copy — an async dispatch; the copy survives the
        # donation of the original buffers by the next step
        copied_state = jax.tree_util.tree_map(jnp.copy, state)
        copied_acc = (
            jax.tree_util.tree_map(jnp.copy, acc) if acc is not None else None
        )
        topology = ckpt.derive_topology(state, self.world_size)
        job = _Job(copied_state, copied_acc, topology, epoch, step, plan_key)
        if self._queue is not None:
            self._ensure_thread()
            try:
                self._queue.put_nowait(job)
            except queue.Full:
                self.stats["skipped_queue_full"] += 1
                self.tracer.end_span(span, skipped="queue_full")
                return False
            self.tracer.end_span(span, enqueued=True)
        else:
            self._write(job)
            self.tracer.end_span(span)
        self._next_due = int(step) + self.cfg.every_steps
        return True

    def flush(self) -> Optional[int]:
        """Block until every in-flight snapshot is on disk; returns the
        step of the last PUBLISHED snapshot (None if none yet). The preempt
        drain calls this first — the in-flight snapshot it waits for is
        work already done, so exit latency is the final delta only."""
        if self._queue is not None and self._thread is not None:
            self._queue.join()
        self.stats["flushes"] += 1
        return self.stats["last_step"]

    def final_snapshot(
        self, state, *, epoch: int, step: int, plan_key, acc=None
    ) -> Optional[str]:
        """The preempt drain's final delta: flush in-flight work, then write
        ``state`` at ``(epoch, step)`` INLINE (the exit path must not race
        its own writer thread). Returns the published path (None off-writer
        or disarmed)."""
        self.flush()
        if not self._is_writer or self._disarmed or not self._addressable(state):
            return None
        if self.stats["last_epoch"] == int(epoch) and self.stats["last_step"] == int(step):
            return self.stats["last_path"]  # flush already published it
        job = _Job(state, acc, ckpt.derive_topology(state, self.world_size),
                   epoch, step, plan_key)
        return self._write(job)

    def close(self) -> None:
        """Flush and stop the writer thread. Idempotent."""
        if self._closed:
            return
        self._closed = True
        if self._queue is not None and self._thread is not None:
            self._queue.join()
            self._queue.put(_STOP)
            self._thread.join(timeout=60)
        if self._errors:
            logger.warning(
                "snapshot writer finished with %d error(s); first: %s",
                len(self._errors), self._errors[0],
            )

    # ----------------------------------------------------------- private --

    def _addressable(self, state) -> bool:
        """Disarm (once, with a warning) when the state holds leaves this
        process cannot serialize without a collective — the cross-host
        weight-update-sharded case. A background thread must never join a
        collective, so those runs keep the epoch-granular save path."""
        if self._disarmed:
            return False
        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                self._disarmed = (
                    "state holds cross-host-sharded leaves (weight-update "
                    "sharding across processes); step snapshots need a "
                    "collective gather the background writer cannot join — "
                    "falling back to epoch-granular checkpoints"
                )
                logger.warning("snapshot engine disarmed: %s", self._disarmed)
                return False
        return True

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._worker, name="tpuddp-snapshot-writer", daemon=True
            )
            self._thread.start()

    def _worker(self) -> None:
        while True:
            job = self._queue.get()
            try:
                if job is _STOP:
                    return
                self._write(job)
            except Exception as e:  # noqa: BLE001 — a failed snapshot must
                # never take down training; the next cadence retries
                logger.exception("snapshot write failed: %s", e)
                self._errors.append(str(e))
            finally:
                self._queue.task_done()

    def _peer_dir(self) -> Optional[str]:
        from tpuddp.resilience import watchdog

        hb = watchdog.heartbeat_dir(self.save_dir)
        if not hb:
            return None
        ring = (jax.process_index() + 1) % max(jax.process_count(), 1)
        return os.path.join(hb, "peer_ckpt", f"ring_{ring}")

    def _spill_peer(self, path: str) -> None:
        """Copy the published snapshot (payload + manifest) into the ring
        neighbor's spill directory — atomic per file, best-effort by the
        no-stall contract (a failed spill is logged, never raised)."""
        peer = self._peer_dir()
        if peer is None:
            return
        try:
            os.makedirs(peer, exist_ok=True)
            for src in (path, integrity.manifest_path(path)):
                if not os.path.exists(src):
                    continue
                dst = os.path.join(peer, os.path.basename(src))
                tmp = dst + ".tmp"
                shutil.copyfile(src, tmp)
                os.replace(tmp, dst)
            if self.keep_last is not None:
                ckpt.prune_checkpoints(peer, self.keep_last, self.prefix)
        except OSError as e:
            logger.warning("peer-redundant spill to %s failed: %s", peer, e)

    def _write(self, job: _Job) -> str:
        """Serialize one snapshot — the background writer's body, also run
        inline for sync mode and the final delta. Same ``checkpoint.save``
        path as a synchronous save: byte-identical output."""
        t0 = time.perf_counter()
        target = ckpt.step_checkpoint_path(
            self.save_dir, job.epoch, job.step, self.prefix
        )
        os.makedirs(self.save_dir, exist_ok=True)
        cursor = {
            "version": ckpt.FORMAT_VERSION,
            "epoch": job.epoch,
            "step": job.step,
            "plan_key": job.plan_key,
        }
        path = ckpt.save(
            target,
            job.state,
            meta={"epoch": job.epoch, "completed": 0, "step": job.step},
            topology=job.topology,
            cursor=cursor,
            cursor_acc=job.acc,
        )
        # chaos hook: corrupt@ckpt_E_sS garbles the published snapshot —
        # restore must then fall back to the next-freshest (or a peer copy)
        faults.maybe_fire(
            "ckpt", name=f"{self.prefix}_{job.epoch}_s{job.step}", path=path
        )
        if self.cfg.peer_redundancy:
            self._spill_peer(path)
        if self.keep_last is not None:
            ckpt.prune_checkpoints(self.save_dir, self.keep_last, self.prefix)
        with self._lock:
            self.stats["snapshots"] += 1
            self.stats["write_s"] += time.perf_counter() - t0
            try:
                self.stats["bytes"] += os.path.getsize(path)
            except OSError:
                pass
            self.stats["last_epoch"] = job.epoch
            self.stats["last_step"] = job.step
            self.stats["last_path"] = path
            sidecar = {
                "async": self.cfg.async_writes,
                "inflight": self.cfg.inflight,
                "peer_redundancy": self.cfg.peer_redundancy,
                "snapshots": self.stats["snapshots"],
                "skipped_queue_full": self.stats["skipped_queue_full"],
                "write_s": round(self.stats["write_s"], 6),
                "bytes": self.stats["bytes"],
            }
        try:
            tmp = writer_stats_path(path) + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(sidecar, f, sort_keys=True)
            os.replace(tmp, writer_stats_path(path))
        except OSError:
            pass
        if self.flight is not None:
            self.flight.note(
                snapshot_last={
                    "epoch": job.epoch, "step": job.step,
                    "path": os.path.basename(path),
                }
            )
        return path
