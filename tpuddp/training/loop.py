"""Epoch driver — parity with the reference's ``run_training_loop``
(multi-GPU-training-torch.py:156-225), TPU-first in the hot path.

Per epoch: ``set_epoch`` reshuffle (toggleable, :175-178), optional RNG probe
(:180-183), train pass, eval pass, barrier (:194), five-scalar metric
aggregation (:198-206), process-0 logging (:209-215), process-0 checkpoint
every ``checkpoint_epoch`` epochs + barrier (:217-223).

Quirk decisions (SURVEY.md §3.5):
- Q1 fixed: the banner says *batches*, not samples.
- Q2 fixed: ``set_epoch`` is applied to the test loader too (harmless for the
  reference's metrics, removes the frozen-eval-order oddity).
- Q5 fixed: metric accumulation stays on device; one host sync per epoch.
- Q6 kept: checkpoint fires at epoch 0 (parity with the reference's
  ``epoch % checkpoint_epoch == 0``).
"""

from __future__ import annotations

import logging
import os
import time
from typing import Optional

import jax
import numpy as np

from tpuddp import seeding
from tpuddp.parallel import collectives as col
from tpuddp.resilience import faults
from tpuddp.resilience import guard as guard_lib
from tpuddp.resilience.preemption import (
    TrainingPreempted,
    auto_resume_requested,
    preemption_requested,
)
from tpuddp.observability import (
    CommBytesCounter,
    MetricsWriter,
    RunTelemetry,
    check_finite,
    make_run_meta,
    maybe_start_profiler,
    stamp,
    stop_profiler,
)
from tpuddp.training import checkpoint as ckpt
from tpuddp.training import pipeline as pipeline_lib
from tpuddp.training import snapshot as snapshot_lib
from tpuddp.utils import batching
from tpuddp.training.step import finalize_metrics

logger = logging.getLogger("tpuddp")


_AUTO_SCAN_CAP = 64  # A/B-measured on AlexNet b128 across three r5 tunnel
# states (RTT ~7, ~23, ~240 ms/dispatch): K=64 beat K=32 in every pairing
# (bad tunnel: 9.8 vs 13.3-15.2 ms/step) — per-dispatch RTT amortization is
# pure win with no semantic cost. This is the depth the bench's CNN rows
# publish — the product default and the bench agree.
_AUTO_SCAN_FALLBACK_CAP = 32  # when the staged-chunk size cannot be known
# bound on one staged (K, batch) chunk — the shared budget every auto depth
# policy (native scan, managed fuse, eval fusion, serving) caps against
_STAGE_BYTES_BUDGET = batching.STAGE_BYTES_BUDGET
_SMALL_PARAM_BYTES = 4 * 1024 * 1024


def resolve_scan_steps(
    scan_steps, n_batches: int, param_bytes=None, batch_nbytes=None
) -> int:
    """Resolve the per-dispatch fusion factor K.

    ``"auto"`` (the default) fuses up to 64 batches per dispatch when the
    epoch has at least that many — the measured per-dispatch runtime latency
    dominates per-step time otherwise (BASELINE.md: ~7x on the toy model
    through a tunneled TPU; the tunnel's RTT swings 7-240 ms between
    sessions and K is the amortization lever). The staged ``(K, batch, ...)``
    super-chunk must stay bounded, so whenever ``batch_nbytes`` (one host
    batch's input bytes) is known the ~256 MB staging budget caps K — for
    EVERY model size: a small model fed large batches still stages
    K x batch bytes, so the budget binds there too. Model size only decides
    the starting cap when batch bytes are unknowable: small models (whole
    parameter set under ~4 MB) start from 64 — dispatch latency dominates
    them even deeper (the bench's toy-MLP K-sweep) — while unknown-size
    batches on non-small models fall back to a conservative 32. Any integer
    pins K explicitly; 1 disables fusion (one dispatch per batch, the
    reference's cadence)."""
    if scan_steps in (None, "auto"):
        small = param_bytes is not None and param_bytes < _SMALL_PARAM_BYTES
        cap = _AUTO_SCAN_CAP if (small or batch_nbytes) else _AUTO_SCAN_FALLBACK_CAP
        # the staging budget binds regardless of model size — a small model
        # on large inputs still stages K x batch bytes (shared cap policy,
        # tpuddp/utils/batching.py)
        cap = batching.resolve_fuse(batch_nbytes, cap=cap)
        return max(1, min(cap, n_batches))
    k = int(scan_steps)
    if k < 1:
        raise ValueError(f"scan_steps must be >= 1 or 'auto', got {scan_steps!r}")
    return k


def _param_bytes(params) -> int:
    return sum(
        int(np.prod(l.shape)) * l.dtype.itemsize
        for l in jax.tree_util.tree_leaves(params)
        if hasattr(l, "shape") and hasattr(l, "dtype")
    )


def _never():
    return False


def _fused_pass(
    ddp, state, loader, scan_k: int, step_one, step_many, probe_cb=None,
    accum: int = 1, poll=preemption_requested, inject_cb=None, tel=None,
    pipeline: Optional[pipeline_lib.PipelineConfig] = None,
    tracer=None, trace_parent=None, comm_attrs=None, snap_cb=None,
    init_acc=None,
):
    """One pass over ``loader`` — the async pipelined runner
    (:mod:`tpuddp.training.pipeline`): K-fused dispatch, a ``depth``-chunk
    staged device queue (host->HBM transfers overlap the previous dispatch's
    compute), and a deferred readback drain. ``pipeline`` (None -> the
    default config) only changes *when* host work happens, never what is
    dispatched: results are bitwise identical at every depth. See
    :func:`tpuddp.training.pipeline.run_pass` for the full contract."""
    return pipeline_lib.run_pass(
        ddp, state, loader, scan_k, step_one, step_many,
        cfg=pipeline if pipeline is not None else pipeline_lib.DEFAULT,
        probe_cb=probe_cb, accum=accum, poll=poll, inject_cb=inject_cb,
        tel=tel, tracer=tracer, trace_parent=trace_parent,
        comm_attrs=comm_attrs, snap_cb=snap_cb, init_acc=init_acc,
    )


def run_training_loop(
    ddp,
    state,
    train_loader,
    test_loader,
    save_dir: Optional[str],
    num_epochs: int = 20,
    checkpoint_epoch: int = 5,
    set_epoch: bool = True,
    print_rand: bool = False,
    data_probe_every: Optional[int] = None,
    start_epoch: int = 0,
    scan_steps="auto",
    per_replica_log: bool = False,
    auto_resume: bool = False,
    reshard_on_mismatch: bool = False,
    keep_last: Optional[int] = None,
    step_stats_every: int = 0,
    run_meta: Optional[dict] = None,
    pipeline=None,
    observability=None,
    snapshot=None,
    log=print,
):
    """Run the full training loop; returns ``(state, history)`` where history
    is a list of per-epoch metric dicts.

    ``ddp``: a DistributedDataParallel (or Accelerator-prepared equivalent)
    exposing shard/train_step/eval_step. Loaders yield host ``(x, y, w)``
    batches (ShardedDataLoader for DP; see tpuddp.data.loader).

    Resilience: ``auto_resume=True`` (or ``$TPUDDP_AUTO_RESUME=1``) restores
    the newest intact checkpoint in ``save_dir`` before training — including a
    preemption-drain emergency save, whose interrupted epoch is redone. A
    SIGTERM/SIGINT during training (see tpuddp.resilience.preemption) is
    polled at batch-group boundaries: the loop writes an emergency checkpoint
    and raises :class:`TrainingPreempted`, which ``spawn.run_ddp_training``
    turns into exit code 75. ``keep_last=K`` prunes all but the K newest
    checkpoints after each save. ``reshard_on_mismatch=True`` (the
    ``training.reshard_on_mismatch`` knob) lets the restore re-shape a
    checkpoint written on a DIFFERENT ``(data, model)`` mesh onto this one
    via the cross-topology reshaper (training/reshard.py) — the elastic
    mesh failover path; the reshard lands typed event rows and, when
    tracing is on, a named ``elastic reshard`` span.

    Numerical guard (``ddp.guard``, resilience/guard.py): the wrap owns the
    in-step firewall; this driver owns the epoch policy — it reads the skip
    counters once per epoch into the history record, runs the desync auditor
    every ``guard.audit_every_n_epochs`` (divergence -> ReplicaDesync/exit 77,
    or rollback), rolls back to the newest intact checkpoint when more than
    ``guard.max_consecutive_skips`` updates were skipped back to back, and
    guards BOTH aggregated losses (``$TPUDDP_DEBUG_NANS``) before any
    checkpoint so a poisoned epoch can never persist its state.

    Telemetry (tpuddp.observability): ``history.jsonl`` opens with a typed
    ``run_meta`` header, every epoch row carries step-time p50/p95/p99/max
    and achieved-MFU fields from the per-dispatch step recorder, and
    ``step_stats_every=N > 0`` additionally emits one ``step_stats`` row per
    N train steps (ONE host-side device fence per window — the compiled step
    program is untouched). ``run_meta`` (the dict) merges entrypoint-level
    fields (config hash, model, dataset) into the header row. Profiling:
    ``$TPUDDP_PROFILE`` (first epoch), ``$TPUDDP_PROFILE_STEPS=a:b`` (step
    window), SIGUSR1 (trace the next epoch of a live run).

    Async pipeline (``pipeline``, the ``training.pipeline`` block — see
    :mod:`tpuddp.training.pipeline`): depth of the staged device chunk
    queue, host loader workers, and the synchronous A/B mode. Bitwise
    identical to the synchronous path at every depth; ``step_stats`` windows
    gain the occupancy fields (host_stall_ms, staging/in-flight depth).

    Live telemetry plane (``observability``, the ``observability`` block —
    ISSUE 10): an opt-in background /metrics exporter fed by the same
    recorder state the history flushes, per-host telemetry shards through
    the heartbeat channel with a main-process pod aggregator + straggler
    detector, and a crash flight recorder dumped on abnormal exits. All
    host-side: the compiled step, the fence cadence, and the HLO are
    untouched with the whole plane on.

    Async step snapshots (``snapshot``, the ``training.snapshot`` block —
    :mod:`tpuddp.training.snapshot`): a background checkpoint engine takes
    device snapshots every N real micro-batches between dispatches (no step
    stall, no HLO change) and records the v4 data cursor. A preempt drain
    then flushes the in-flight snapshot and writes a final step delta
    instead of re-serializing the whole state; auto-resume from a cursor-
    bearing snapshot continues the interrupted epoch AT the recorded step —
    zero batches replayed, loss trajectory bitwise-equal to an
    uninterrupted same-seed run — and guard rollbacks restore to the last
    good STEP, not epoch. Off (None) keeps the pre-v4 epoch-granular
    contract, including redo-the-interrupted-epoch (deprecated — see README
    "Async checkpointing & exact resume").
    """
    from tpuddp import config as cfg_lib
    from tpuddp.observability import aggregate as agg_lib
    from tpuddp.observability import exporter as exp_lib
    from tpuddp.observability import flight as flight_lib
    from tpuddp.observability import trace as trace_lib
    from tpuddp.resilience import watchdog as wd_lib

    is_main = jax.process_index() == 0
    pipeline = pipeline_lib.resolve_pipeline(pipeline)
    pbytes = _param_bytes(state.params) if hasattr(state, "params") else None
    eval_scan_steps = (
        resolve_scan_steps(
            scan_steps, len(test_loader), pbytes,
            getattr(test_loader, "batch_nbytes", None),
        )
        if hasattr(ddp, "eval_step_many")
        else 1
    )
    scan_steps = resolve_scan_steps(
        scan_steps, len(train_loader), pbytes,
        getattr(train_loader, "batch_nbytes", None),
    )
    accum = int(getattr(ddp, "grad_accumulation", 1) or 1)
    if accum > 1:
        # chunks must hold whole accumulation cycles: round K up to the
        # cycle length, then down to a multiple of it
        scan_steps = max(accum, (scan_steps // accum) * accum)
        bnb = getattr(train_loader, "batch_nbytes", None)
        if bnb and scan_steps * bnb > _STAGE_BYTES_BUDGET:
            # respect the staging budget in whole cycles; one cycle is the
            # floor (the accumulation step needs whole cycles), warn if even
            # that exceeds the budget
            scan_steps = max(
                accum, (_STAGE_BYTES_BUDGET // bnb) // accum * accum
            )
            if scan_steps * bnb > _STAGE_BYTES_BUDGET:
                logger.warning(
                    "gradient_accumulation_steps=%d forces a staged chunk of "
                    "%.0f MB (one whole cycle), over the ~%d MB staging "
                    "budget; reduce the accumulation depth or batch size if "
                    "the host/device cannot hold it",
                    accum, scan_steps * bnb / 1e6, _STAGE_BYTES_BUDGET // 2**20,
                )
    want_resume = auto_resume or auto_resume_requested()
    if want_resume and save_dir is None and is_main:
        log("Auto-resume requested but no save_dir configured; starting fresh.")

    history = []
    # ---- live telemetry plane (observability/{exporter,aggregate,flight}):
    # the flight ring tees every history record (every process keeps one);
    # the exporter/aggregator start below once the telemetry bundle exists.
    obs_cfg = cfg_lib.resolve_observability(observability)
    # causal tracing plane (observability/trace.py, default OFF): epoch ->
    # stage/dispatch/collective/readback span trees, exported as
    # trace_train.json at drain and served on /trace. Host bracketing only.
    tracer = trace_lib.tracer_from_config(obs_cfg, "train", run_dir=save_dir)
    flight = None
    if obs_cfg["flight_recorder"] and save_dir is not None:
        flight = flight_lib.install(flight_lib.FlightRecorder(
            save_dir, capacity=int(obs_cfg["flight_capacity"]),
        ))
        if tracer.enabled:
            # a crash dump embeds the still-open spans: the exact stage the
            # process died in, not just the last flushed window
            flight.add_context("open_spans", tracer.open_span_summaries)
        if obs_cfg.get("advisor") or os.environ.get(cfg_lib.TUNE_OVERLAY_ENV):
            # advisor-armed runs: a preempt/crash must not lose the pending
            # recommendation — the dump carries the top advice so the next
            # launch (or a human) can act on what this run already learned
            from tpuddp.observability import advisor as advisor_lib
            flight.add_context(
                "pending_tune",
                lambda: advisor_lib.pending_summary(save_dir),
            )
    metrics_writer = MetricsWriter(save_dir, flight=flight)
    # ---- async step-granular snapshots (training/snapshot.py): the engine
    # copies state on-device between dispatches and serializes on a
    # background writer; pending_cursor carries a restored v4 data cursor to
    # the epoch that consumes it (exact mid-epoch resume, zero replay).
    snap_cfg = snapshot_lib.resolve_snapshot(snapshot)
    snap_engine = None
    if snap_cfg.enabled and save_dir is not None:
        snap_engine = snapshot_lib.SnapshotEngine(
            save_dir, snap_cfg,
            world_size=getattr(ddp, "world_size", None),
            keep_last=keep_last,
            tracer=tracer, flight=flight,
        )
    pending_cursor = {"c": None}
    # the run's ONE trace id: minted before the restore below so an elastic
    # reshard episode lands as a named span in the SAME trace as the epochs
    # it precedes — the tracing plane shows recovery, not a gap
    run_trace_id = tracer.new_trace()
    # elastic resume (ISSUE 7 / ISSUE 16): restore_latest reshards a
    # checkpoint written on a different world size — and, with
    # reshard_on_mismatch, a different (data, model) MESH SHAPE — onto this
    # one (training/checkpoint.py + training/reshard.py) and hands back the
    # typed topology-change events, written below once the history's
    # run_meta header exists.
    reshard_log = []
    if want_resume and save_dir is not None:
        resume_span = tracer.start_span(
            "auto-resume restore", trace_lib.KIND_ACTION,
            trace_id=run_trace_id, tid="train",
        )
        resume_cursor = []
        state, resumed = ckpt.restore_latest(
            save_dir, state,
            world_size=getattr(ddp, "world_size", None),
            model_size=getattr(ddp, "model_size", None),
            reshard_log=reshard_log,
            reshard_on_mismatch=reshard_on_mismatch,
            cursor_out=resume_cursor,
        )
        if resume_cursor:
            # a v4 step snapshot: the cursor's epoch resumes AT its step
            # (the epoch below that consumes pending_cursor verifies the
            # plan key first — a changed data order falls back to redo)
            pending_cursor["c"] = resume_cursor[-1]
            if flight is not None:
                flight.note(snapshot_resume={
                    "epoch": resume_cursor[-1].get("epoch"),
                    "step": resume_cursor[-1].get("step"),
                    "provenance": resume_cursor[-1].get("provenance"),
                    "path": os.path.basename(
                        resume_cursor[-1].get("path") or ""
                    ),
                })
        if resumed > start_epoch:
            start_epoch = resumed
            if is_main:
                log(f"Auto-resume: continuing from epoch {start_epoch}.")
        topo_ev = next(
            (ev for ev in reshard_log if ev.get("event") == "topology_change"),
            None,
        )
        if topo_ev is not None:
            # name the reshard episode on every observability surface: a
            # child span in the run trace, a flight-recorder note, and (just
            # below) the typed history event rows
            reshard_span = tracer.start_span(
                "elastic reshard", trace_lib.KIND_ACTION,
                parent=resume_span,
                attrs={k: topo_ev.get(k) for k in (
                    "from_world", "to_world", "from_model", "to_model",
                    "checkpoint", "residual",
                )},
            )
            tracer.end_span(
                reshard_span, resharded_leaves=len(topo_ev.get(
                    "resharded_leaves") or ()),
            )
            if flight is not None:
                # namespaced note key: any later crash dump carries the
                # episode under notes["elastic_reshard"]
                flight.note(elastic_reshard={
                    k: topo_ev.get(k) for k in (
                        "from_world", "to_world", "from_model", "to_model",
                        "checkpoint",
                    )
                })
        tracer.end_span(
            resume_span, resumed_epoch=start_epoch,
            resharded=bool(reshard_log),
        )
    # gradient-comm wire-bytes accounting (parallel/comm.py counter): one
    # optimizer update per accumulation cycle; the payload per update is
    # static, so the counter is free host arithmetic next to the device step
    comm_counter = CommBytesCounter(
        getattr(ddp, "grad_comm_bytes_per_step", None)
    )
    profiling = maybe_start_profiler(save_dir)  # $TPUDDP_PROFILE hook

    # ---- numerical guard (resilience/guard.py): the ddp wrap owns the
    # config; the driver owns the epoch-level policy — skip accounting,
    # periodic desync audits, rollback-to-last-good.
    guard_cfg = guard_lib.resolve_guard(getattr(ddp, "guard", None))

    # ---- telemetry (tpuddp.observability): typed run_meta header first,
    # then the per-dispatch step recorder + on-demand profiling triggers.
    meta_extra = {
        "api": "native",
        "scan_steps": scan_steps,
        "grad_accumulation": accum,
        "start_epoch": start_epoch,
        "num_epochs": num_epochs,
        "step_stats_every": int(step_stats_every or 0),
        "pipeline": pipeline.as_dict(),
        "grad_comm_bytes_per_update": getattr(
            ddp, "grad_comm_bytes_per_step", None
        ),
        "grad_comm_bytes_per_update_f32": getattr(
            ddp, "grad_comm_bytes_per_step_f32", None
        ),
        # comm compression v2 accounting: which wire topology the bytes
        # crossed, the top-k density, and the intra/inter-host hop split
        # (the hierarchical topology's whole point — parallel/comm.py)
        "comm_density": getattr(ddp, "topk_density", None),
        "grad_comm_bytes_inter_host": getattr(
            ddp, "grad_comm_bytes_inter_host", None
        ),
        "grad_comm_bytes_intra_host": getattr(
            ddp, "grad_comm_bytes_intra_host", None
        ),
        **(run_meta or {}),
    }
    topo_change = next(
        (ev for ev in reshard_log if ev.get("event") == "topology_change"), None
    )
    if topo_change is not None:
        # the header states the elastic provenance: this run CONTINUES a
        # trajectory that was training on a different world size (and,
        # after a mesh failover, a different model width)
        meta_extra["resumed_from_world"] = topo_change.get("from_world")
        if topo_change.get("from_model") is not None:
            meta_extra["resumed_from_model"] = topo_change.get("from_model")
    # exporter starts BEFORE the header so the header can record the BOUND
    # port (ephemeral binds resolve at start); sources attach once the
    # telemetry bundle exists below
    exporter = exp_lib.exporter_from_config(obs_cfg, run_dir=save_dir)
    if exporter is not None:
        exporter.start()
        if tracer.enabled:
            exporter.set_trace_source(tracer.endpoint_payload)
    obs_meta = {
        "exporter": exporter.describe() if exporter is not None else False,
        "aggregate": bool(obs_cfg["aggregate"]),
        "straggler_ratio": float(obs_cfg["straggler_ratio"]),
        "straggler_windows": int(obs_cfg["straggler_windows"]),
        "flight_recorder": (
            flight.describe() if flight is not None else False
        ),
    }
    # v10 comm block: the gradient-exchange execution provenance — did the
    # step run segmented-backward (comm_overlap) and over how many segments,
    # or the barrier step and why (null on wraps predating the knob)
    overlap_meta = getattr(ddp, "comm_overlap_meta", None)
    comm_block = (
        {"overlap": dict(overlap_meta)} if overlap_meta is not None else None
    )
    metrics_writer.write(make_run_meta(
        mesh=getattr(ddp, "mesh", None),
        world_size=getattr(ddp, "world_size", None),
        comm_hook=getattr(ddp, "comm_hook", None),
        comm_topology=getattr(ddp, "comm_topology", "flat"),
        guard=guard_cfg,
        observability=obs_meta,
        # v8 mesh block: names the TP rule table when the mesh carries a
        # real model axis (None on pure-DP wraps)
        tp_rules_hash=getattr(ddp, "tp_rules_hash", None),
        # v9 tracing block: ring capacity + artifact name (null = off)
        tracing=tracer.describe(),
        # v11 snapshot block: async step-checkpoint engine provenance
        # (config + writer identity), or False when the engine is off
        snapshot=(
            snap_engine.describe() if snap_engine is not None
            else (snap_cfg.as_dict() if snap_cfg.enabled else False)
        ),
        comm=comm_block,
        # v12 tuning block: tune-overlay provenance when this process was
        # relaunched under $TPUDDP_TUNE_OVERLAY (null = advisor off / no
        # overlay — the bitwise-identical default)
        tuning=cfg_lib.tuning_provenance_from_env(),
        extra=meta_extra,
    ))
    for ev in reshard_log:
        metrics_writer.write(stamp("event", ev))
    # FLOPs probe for the MFU fields: lower (never compile) the single-step
    # program once, at the first epoch boundary — only when the per-batch
    # step exists (grad accumulation refuses it) and shapes are capturable.
    flops_lower_fn = None
    if accum == 1 and hasattr(ddp, "train_step"):
        try:
            state_struct = jax.tree_util.tree_map(
                lambda l: jax.ShapeDtypeStruct(l.shape, l.dtype), state
            )
        except Exception:
            state_struct = None
        if state_struct is not None:
            def flops_lower_fn():
                if not tel.batch_struct:
                    raise ValueError("no batch structure captured")
                return jax.jit(
                    lambda s, b: ddp.train_step(s, b)
                ).lower(state_struct, tel.batch_struct)
    ddp_mesh = getattr(ddp, "mesh", None)
    tel = RunTelemetry(
        writer=metrics_writer,
        save_dir=save_dir,
        step_stats_every=step_stats_every,
        world_size=getattr(ddp, "world_size", 1) or 1,
        flops_lower_fn=flops_lower_fn,
        device_kind=(
            ddp_mesh.devices.flat[0].device_kind if ddp_mesh is not None else None
        ),
    )
    # cross-host aggregation: every process publishes its shard through the
    # heartbeat channel; process 0 merges + detects stragglers. Inert on
    # single-process runs (there is no pod to aggregate).
    aggregator = None
    shard_dir = None
    if obs_cfg["aggregate"] and jax.process_count() > 1:
        shard_dir = wd_lib.heartbeat_dir(save_dir)
        if shard_dir is not None:
            os.makedirs(shard_dir, exist_ok=True)
            if is_main:
                aggregator = agg_lib.PodAggregator(
                    shard_dir,
                    jax.process_count(),
                    writer=metrics_writer,
                    straggler_ratio=float(obs_cfg["straggler_ratio"]),
                    straggler_windows=int(obs_cfg["straggler_windows"]),
                )
    tel.attach_live(
        exporter=exporter,
        aggregator=aggregator,
        shard_dir=shard_dir,
        process_id=jax.process_index(),
    )

    prev_total_skips = (
        guard_lib.read_skip_counters(state)[0] if guard_cfg.enabled else 0
    )
    rollback_count = {"n": 0}

    def rollback_to_last_good(cur_state, epoch, reason):
        """Restore the newest integrity-verified checkpoint and hand back
        ``(state, epoch_to_redo)``. The caller re-enters the epoch loop
        there, so ``set_epoch`` re-derives the redone epoch's data order.
        With step snapshots armed the newest checkpoint is usually a v4
        STEP snapshot — the rollback then lands on the last good STEP, not
        epoch: its cursor goes through ``pending_cursor`` and the redone
        epoch continues at the recorded step. The rollback is a recorded
        event in history.jsonl, and a bounded one — replaying a
        persistently-poisoned epoch forever is not recovery."""
        rollback_count["n"] += 1
        if rollback_count["n"] > guard_cfg.max_rollbacks:
            raise RuntimeError(
                f"guard rollback limit ({guard_cfg.max_rollbacks}) exceeded; "
                f"last trigger: {reason}. The failure recurs after restoring "
                "known-good state — a systematic divergence, not a transient."
            )
        rb_log = []
        rb_cursor = []
        restored, redo_epoch = ckpt.restore_latest(
            save_dir, cur_state,
            world_size=getattr(ddp, "world_size", None),
            model_size=getattr(ddp, "model_size", None),
            reshard_log=rb_log,
            reshard_on_mismatch=reshard_on_mismatch,
            cursor_out=rb_cursor,
        )
        resume_step = None
        if rb_cursor:
            pending_cursor["c"] = rb_cursor[-1]
            resume_step = rb_cursor[-1].get("step")
        metrics_writer.write(stamp("event", {
            "event": "rollback",
            "epoch": epoch,
            "resume_epoch": redo_epoch,
            "resume_step": resume_step,
            "reason": reason,
        }))
        for ev in rb_log:
            metrics_writer.write(stamp("event", ev))
        if is_main:
            log(
                f"Guard rollback ({reason}): restored last-good checkpoint, "
                f"redoing from epoch {redo_epoch}."
            )
            if resume_step is not None:
                log(
                    f"Rollback target is a step snapshot: epoch {redo_epoch} "
                    f"continues at step {resume_step}."
                )
        return restored, redo_epoch

    def can_roll_back() -> bool:
        return save_dir is not None and ckpt.latest(save_dir) is not None

    # ---- step-site chaos hooks (resilience/faults.py): wired only while an
    # un-fired step fault is armed, so normal runs pay nothing per batch. The
    # step index is the global train micro-batch count from loop entry.
    # nan@step=N poisons the batch (the guard-firewall proof);
    # preempt@step=N / crash@step=N kill the run MID-epoch — the elastic
    # chaos matrix's resize scenarios (resume redoes the interrupted epoch
    # from the saved mid-epoch state, possibly on a different world size).
    nan_inject = None
    if faults.has_step_fault():
        _nan_step = {"i": 0}

        def nan_inject(host_batch):
            i = _nan_step["i"]
            _nan_step["i"] += 1
            faults.maybe_fire("step", step=i)  # process-level kinds
            return faults.maybe_corrupt_batch(host_batch, i)

    multihost = jax.process_count() > 1
    # single-host: poll the drain flag at every batch-group boundary.
    # multi-host: never inside a pass — one host returning early while peers
    # still issue step collectives wedges the pod; drains happen only at the
    # globally-agreed epoch boundary below.
    poll = _never if multihost else preemption_requested

    def drain_requested():
        if not multihost:
            return preemption_requested()
        # SIGTERMs land on hosts milliseconds apart; before anyone enters the
        # save collectives all hosts must agree a drain is on, or the ones
        # that didn't see the flag yet deadlock the pod. Process 0's flag is
        # the decision; this broadcast is one tiny per-epoch collective.
        return bool(col.broadcast_one_to_all(np.asarray(preemption_requested())))

    def emergency_stop(epoch, completed=False, partial=None):
        """Preemption drain: one atomic full-state save, then the distinct
        exit path via TrainingPreempted. ``completed=False`` (the default)
        marks a mid-train-pass drain — resume redoes ``epoch`` from the saved
        state. ``completed=True`` is the eval-pass interruption: every
        optimizer update of ``epoch`` is already applied, so the save counts
        as end-of-epoch and resume starts at ``epoch + 1`` (re-training it
        would double-apply the whole epoch); only its eval metrics are lost.

        With the snapshot engine armed, a mid-train-pass drain (``partial``:
        the epoch's progress dict + partial accumulator) reuses the async
        writer's flush path instead of re-serializing from scratch: flush
        the in-flight snapshot (work already done), then write only the
        final step delta. Resume then continues AT the drained step."""
        path = None
        flushed_step = None
        snap_drain = (
            snap_engine is not None and not completed and partial is not None
        )
        if save_dir is not None:
            if snap_drain:
                flushed_step = snap_engine.flush()
                path = snap_engine.final_snapshot(
                    state, epoch=epoch, step=int(partial["step"]),
                    plan_key=partial.get("plan_key"), acc=partial.get("acc"),
                )
            if path is None:
                snap_drain = False
                path = ckpt.save_on_main(
                    save_dir, epoch, state, completed=completed,
                    world_size=getattr(ddp, "world_size", None),
                )
                if is_main:
                    log(f"Preempted: emergency checkpoint for epoch {epoch} saved.")
            elif is_main:
                log(
                    f"Preempted: drained snapshot writer (flushed step "
                    f"{flushed_step}) and saved final step snapshot for "
                    f"epoch {epoch} step {int(partial['step'])}."
                )
        # the drain's event row, fsync'd NOW: the SIGKILL that follows the
        # grace window must not be able to eat the post-mortem record
        event = {
            "event": "preempt",
            "epoch": epoch,
            "completed": bool(completed),
            "step": tel.recorder.global_step,
        }
        if snap_drain:
            event["snapshot_step"] = int(partial["step"])
        metrics_writer.write(stamp("event", event))
        metrics_writer.sync()
        # the exit-75 flight recording: the writer tee above means the
        # preempt event (and the last windows before it) are in the ring
        if flight is not None:
            notes = dict(
                emergency_checkpoint=path,
                emergency_epoch=epoch,
                emergency_step=tel.recorder.global_step,
            )
            if snap_drain:
                # the chaos contract: the recording NAMES the flushed step
                # (the last snapshot the writer published before the final
                # delta) and the final step the drain itself wrote
                notes["snapshot_flushed_step"] = flushed_step
                notes["snapshot_final_step"] = int(partial["step"])
            flight.note(**notes)
            flight.dump("preempt")
        raise TrainingPreempted(epoch, path)

    if is_main:
        log(
            f"Training on {len(train_loader)} batches, test on {len(test_loader)} batches"
        )

    # the whole run is ONE trace: every epoch span (and its stage/dispatch/
    # collective/readback children) shares run_trace_id, minted above before
    # the auto-resume restore so a reshard episode rides the same tree. The
    # comm annotation only arms on the train pass of a hooked run — eval
    # dispatches carry no gradient exchange.
    epoch_span = None
    comm_attrs = None
    _overlap_on = bool((overlap_meta or {}).get("enabled"))
    if tracer.enabled and (
        getattr(ddp, "comm_hook", "none") != "none" or _overlap_on
    ):
        comm_attrs = {
            "hook": getattr(ddp, "comm_hook", "none"),
            "topology": getattr(ddp, "comm_topology", "flat"),
            "wire_bytes_per_update": getattr(
                ddp, "grad_comm_bytes_per_step", None
            ),
            "wire_bytes_per_update_f32": getattr(
                ddp, "grad_comm_bytes_per_step_f32", None
            ),
            "inter_host_bytes_per_update": getattr(
                ddp, "grad_comm_bytes_inter_host", None
            ),
        }
        if _overlap_on:
            # segmented-backward overlap: one collective span per backward
            # segment (pipeline.run_pass fans these out), each naming its
            # layer range and bucket count so trace_breakdown.py can show
            # the interleaving visually
            comm_attrs["overlap"] = True
            comm_attrs["segments"] = [
                {
                    "segment": i,
                    "layers": list(seg.layers),
                    "flat": list(seg.flat),
                    "buckets": len(seg.buckets),
                }
                for i, seg in enumerate(getattr(ddp, "_segments", ()) or ())
            ]

    try:
        epoch = start_epoch
        while epoch < num_epochs:
            faults.maybe_fire("epoch", epoch=epoch)  # $TPUDDP_FAULT chaos hook
            if drain_requested():
                emergency_stop(epoch)
            if (
                guard_cfg.enabled
                and guard_cfg.audit_every_n_epochs
                and (epoch - start_epoch) % guard_cfg.audit_every_n_epochs == 0
                and getattr(ddp, "mesh", None) is not None
            ):
                # desync audit: ONE fingerprint reduction over the parameter
                # tree per audited epoch (guard.audit_params cost model) —
                # the periodic re-run of the wrap-time verify
                bad_leaf = guard_lib.audit_params(
                    ddp.mesh, state.params,
                    specs=getattr(ddp, "tp_param_specs", None),
                )
                if bad_leaf is not None:
                    metrics_writer.write(stamp(
                        "event",
                        {"event": "desync", "epoch": epoch, "leaf": bad_leaf},
                    ))
                    if guard_cfg.on_desync == "rollback" and can_roll_back():
                        state, epoch = rollback_to_last_good(
                            state, epoch, f"replica desync at leaf {bad_leaf}"
                        )
                        prev_total_skips = guard_lib.read_skip_counters(state)[0]
                        continue
                    # no checkpoint to fall back to (or exit policy): the
                    # distinct code 77 requeues into auto-resume
                    raise guard_lib.ReplicaDesync(
                        bad_leaf, where=f"epoch {epoch} audit"
                    )
            t0 = time.perf_counter()
            tel.start_epoch(epoch)
            epoch_span = tracer.start_span(
                f"epoch {epoch}", trace_lib.KIND_EPOCH,
                trace_id=run_trace_id, tid="train",
                attrs={"epoch": epoch},
            )
            if is_main:
                log(f"Process {jax.process_index()}, Epoch {epoch}")
            if set_epoch:
                # Per-epoch reshuffle; without it every epoch replays epoch-0
                # order (the pitfall toggle, reference :175-178 / README.md:82-84).
                train_loader.set_epoch(epoch)
                test_loader.set_epoch(epoch)
                if is_main:
                    log(f"DistributedSampler.set_epoch: {set_epoch}")

            if print_rand:
                log(f"Process {jax.process_index()}, {seeding.rng_probe_string()}")

            # ---- exact mid-epoch resume: a v4 cursor restored for THIS epoch
            # skips the already-applied prefix of the batch plan (zero batches
            # replayed) instead of redoing the epoch. The cursor's plan key
            # must match what this loader would produce for this epoch — a
            # mismatch (different sampler config, resharded data order) falls
            # back to the legacy redo-the-epoch path. ----
            resume_skip = None
            cur = pending_cursor["c"]
            if cur is not None and int(cur.get("epoch", -1)) == epoch:
                pending_cursor["c"] = None
                if cur.get("plan_key"):
                    expect = snapshot_lib.epoch_plan_key(train_loader, epoch)
                    if cur["plan_key"] == expect:
                        resume_skip = cur
                        if is_main:
                            log(
                                f"Exact resume: epoch {epoch} continues at "
                                f"step {int(cur['step'])} (zero batches "
                                f"replayed)."
                            )
                    else:
                        logger.warning(
                            "Step snapshot plan key mismatch for epoch %d "
                            "(%s != %s): data order changed, redoing the "
                            "epoch from the restored state.",
                            epoch, cur["plan_key"], expect,
                        )
                else:
                    logger.warning(
                        "Step snapshot for epoch %d carries no plan key "
                        "(resharded restore): redoing the epoch.", epoch,
                    )
            elif cur is not None and int(cur.get("epoch", -1)) != epoch:
                pending_cursor["c"] = None

            base_step = int(resume_skip["step"]) if resume_skip else 0
            pass_loader = train_loader
            init_acc = None
            if base_step > 0:
                pass_loader = snapshot_lib.EpochTailLoader(
                    train_loader, base_step
                )
                init_acc = snapshot_lib.acc_from_cursor(resume_skip)

            # snapshot engine arming for this epoch: the snap_cb fires between
            # step dispatches (post-dispatch, pre-next-stage) so the staged
            # queue never drains — the snapshot is an async on-device copy,
            # serialized off-thread.
            snap_cb = None
            epoch_prog = None
            if snap_engine is not None:
                plan_key = snapshot_lib.epoch_plan_key(train_loader, epoch)
                epoch_prog = {
                    "epoch": epoch, "step": base_step, "plan_key": plan_key,
                }
                snap_engine.begin_epoch(epoch, base_step)
                snap_engine.trace_parent = epoch_span

                def snap_cb(st, batches_done, drain, _base=base_step,
                            _ep=epoch, _pk=plan_key, _prog=epoch_prog):
                    _prog["step"] = _base + batches_done
                    snap_engine.maybe(
                        st, epoch=_ep, step=_base + batches_done,
                        plan_key=_pk, drain=drain,
                    )

            # ---- train pass (hot loop: one jitted step per batch, or per
            # `scan_steps` batches fused into a single lax.scan dispatch) ----
            def train_probe(batch_idx, host_batch):
                if data_probe_every and batch_idx % data_probe_every == 0:
                    probe = getattr(train_loader, "probe_fingerprint", None)
                    if probe is not None:
                        log(f"TRAIN: Batch {batch_idx}, Data {probe(host_batch[0])}")

            state, train_acc, interrupted = _fused_pass(
                ddp, state, pass_loader, scan_steps,
                ddp.train_step, ddp.train_step_many, probe_cb=train_probe,
                accum=accum, poll=poll, inject_cb=nan_inject, tel=tel,
                pipeline=pipeline, tracer=tracer, trace_parent=epoch_span,
                comm_attrs=comm_attrs, snap_cb=snap_cb, init_acc=init_acc,
            )
            if interrupted:
                emergency_stop(
                    epoch,
                    partial=(
                        {**epoch_prog, "acc": train_acc}
                        if epoch_prog is not None else None
                    ),
                )

            # ---- eval pass (same K-fused dispatch + upload lookahead; without
            # it the eval epoch is per-batch dispatch-bound). State threads
            # through untouched. ----
            _, eval_acc, interrupted = _fused_pass(
                ddp, state, test_loader, eval_scan_steps,
                lambda s, b: (s, ddp.eval_step(s, b)),
                lambda s, b: (s, ddp.eval_step_many(s, b)),
                poll=poll, pipeline=pipeline,
                tracer=tracer, trace_parent=epoch_span,
            )
            if interrupted:
                # The train pass landed every optimizer update of this epoch
                # (that is what completed=True means), so the epoch row must
                # land too: resume starts at epoch + 1 and never rewrites it,
                # and a drain that raced the eval pass would otherwise leave a
                # permanent hole in history.jsonl. Eval metrics are honestly
                # NaN — same shape as the empty-test-loader row.
                if train_acc is not None:
                    tm = finalize_metrics({"train": train_acc})["train"]
                    epoch_time = time.perf_counter() - t0
                    epoch_updates = -(-len(train_loader) // accum)
                    comm_counter.add_updates(epoch_updates)
                    record = {
                        "epoch": epoch,
                        "train_loss": tm["loss_sum"] / max(tm["n"], 1.0),
                        "test_loss": float("nan"),
                        "test_accuracy": float("nan"),
                        "train_samples": tm["n"],
                        "test_samples": 0.0,
                        "epoch_time_s": epoch_time,
                        "samples_per_sec": tm["n"] / max(epoch_time, 1e-9),
                    }
                    record.update(tel.end_epoch())
                    record.update(comm_counter.snapshot(epoch_updates))
                    if guard_cfg.enabled:
                        total_skips, _ = guard_lib.read_skip_counters(state)
                        record["skipped_steps"] = total_skips
                        record["skipped_steps_epoch"] = (
                            total_skips - prev_total_skips
                        )
                    record = stamp("epoch", record)
                    history.append(record)
                    metrics_writer.write(record)
                emergency_stop(epoch, completed=True)

            if train_acc is None:
                raise RuntimeError(
                    "train loader yielded no batches this epoch; check the "
                    "dataset and batch size"
                )

            # Sync all processes before aggregating (reference :194).
            col.barrier("tpuddp_epoch", wait_for=(train_acc, eval_acc))

            if (
                per_replica_log
                and eval_acc is not None
                # per-replica values are host-fetchable only when this process can
                # address every shard (single-host); multi-host keeps the line out
                and getattr(train_acc["loss_sum"], "is_fully_addressable", True)
            ):
                # pre-aggregation per-device loss lines (reference :186-191);
                # ONE host fetch for all four arrays, not four round trips
                tl, tn, el, en = jax.device_get(
                    (
                        train_acc["loss_sum"],
                        train_acc["n"],
                        eval_acc["loss_sum"],
                        eval_acc["n"],
                    )
                )
                def _count(v):
                    # a poisoned batch (e.g. an injected NaN sample weight)
                    # makes the weighted count non-finite; the post-mortem
                    # log line must print it, not crash on int(NaN)
                    return int(v) if np.isfinite(v) else float(v)

                for r in range(tl.size):
                    log(
                        f"Train loss on replica {r}: {tl[r] / max(tn[r], 1):.4f} "
                        f"based on {_count(tn[r])} samples"
                    )
                for r in range(el.size):
                    log(
                        f"Test loss on replica {r}: {el[r] / max(en[r], 1):.4f} "
                        f"based on {_count(en[r])} samples"
                    )

            # Aggregate the five scalars (reference :198-204) in ONE fused
            # cross-device pass + one host fetch.
            combined = {"train": train_acc}
            if eval_acc is not None:
                combined["eval"] = eval_acc
            sums = finalize_metrics(combined)
            train_m, eval_m = sums["train"], sums.get("eval")
            train_loss = train_m["loss_sum"] / max(train_m["n"], 1.0)
            if eval_m is not None:
                test_loss = eval_m["loss_sum"] / max(eval_m["n"], 1.0)
                test_accuracy = 100.0 * eval_m["correct"] / max(eval_m["n"], 1.0)
            else:  # empty test loader: report train-only metrics
                eval_m = {"n": 0.0}
                test_loss = float("nan")
                test_accuracy = float("nan")

            epoch_time = time.perf_counter() - t0
            # optimizer updates this epoch: one per accumulation cycle over
            # the dispatched micro-batches (the padded tail rounds up)
            epoch_updates = -(-len(train_loader) // accum)
            comm_counter.add_updates(epoch_updates)
            record = {
                "epoch": epoch,
                "train_loss": train_loss,
                "test_loss": test_loss,
                "test_accuracy": test_accuracy,
                "train_samples": train_m["n"],
                "test_samples": eval_m["n"],
                "epoch_time_s": epoch_time,
                "samples_per_sec": (train_m["n"] + eval_m["n"]) / max(epoch_time, 1e-9),
            }
            # step-time percentiles + achieved-MFU from the train-pass
            # recorder (the finalize_metrics fetch above already fenced the
            # device, so the aggregate wall time is honest)
            record.update(tel.end_epoch())
            record.update(comm_counter.snapshot(epoch_updates))

            # ---- guard skip accounting: ONE tiny counter fetch per epoch.
            epoch_skips = consec_skips = 0
            if guard_cfg.enabled:
                total_skips, consec_skips = guard_lib.read_skip_counters(state)
                epoch_skips = total_skips - prev_total_skips
                prev_total_skips = total_skips
                record["skipped_steps"] = total_skips
                record["skipped_steps_epoch"] = epoch_skips

            # live-plane gauges the recorder cannot see: last epoch losses,
            # guard skips, cumulative comm bytes (host dict updates only)
            tel.update_live(
                train_loss=train_loss,
                test_loss=test_loss,
                test_accuracy=test_accuracy,
                skipped_steps=record.get("skipped_steps", 0),
                grad_comm_bytes_total=comm_counter.total_bytes,
            )
            if aggregator is not None:
                aggregator.update()  # epoch-boundary merge (windows may be off)
            record = stamp("epoch", record)
            history.append(record)
            metrics_writer.write(record)  # post-mortem row always lands
            if epoch_skips:
                # the firewall's skips as a discrete event next to the epoch
                # fields, so event timelines see them without scanning rows
                metrics_writer.write(stamp("event", {
                    "event": "skipped_updates",
                    "epoch": epoch,
                    "count": epoch_skips,
                    "total": record["skipped_steps"],
                }))
            # $TPUDDP_DEBUG_NANS: BOTH aggregated losses are guarded BEFORE
            # any checkpoint below — a poisoned epoch must never persist its
            # state (the pre-fix ordering only checked the train loss, so a
            # finite-train/NaN-test epoch could still be checkpointed).
            check_finite(train_loss, "train loss")
            if eval_m["n"]:  # the empty-test-loader NaN placeholder is benign
                check_finite(test_loss, "test loss")

            if profiling and epoch == start_epoch:
                stop_profiler()  # trace the first epoch only
                profiling = False

            if is_main:
                # Exact reference log format (:209-215).
                log(
                    f"Epoch {epoch + 1}/{num_epochs}, "
                    f"Train Loss: {train_loss:.4f}, "
                    f"Test Loss: {test_loss:.4f}, "
                    f"Test Accuracy: {test_accuracy:.2f}%"
                )

            if consec_skips > guard_cfg.max_consecutive_skips:
                # the firewall is skipping updates back to back: training is
                # not progressing, and the last pre-skip metrics/EF residual
                # may already be suspect — restore last-good instead of
                # checkpointing a wedged trajectory
                if can_roll_back():
                    tracer.end_span(epoch_span, rollback="consecutive_skips")
                    state, epoch = rollback_to_last_good(
                        state, epoch,
                        f"{consec_skips} consecutive non-finite updates skipped",
                    )
                    prev_total_skips = guard_lib.read_skip_counters(state)[0]
                    continue
                raise FloatingPointError(
                    f"non-finite gradients forced {consec_skips} consecutive "
                    "skipped updates and no checkpoint exists to roll back to "
                    "(set save_dir / checkpoint_epoch to arm rollback)"
                )

            if save_dir is not None and epoch % checkpoint_epoch == 0:
                if epoch_skips:
                    # a guarded state is safe to checkpoint (skipped updates
                    # are bitwise no-ops), but never silently: the save and
                    # the skips it survived are one logged fact
                    logger.warning(
                        "checkpointing epoch %d after %d skipped update(s) "
                        "this epoch (total %d)",
                        epoch, epoch_skips, record["skipped_steps"],
                    )
                ckpt.save_on_main(
                    save_dir, epoch, state, keep_last=keep_last,
                    world_size=getattr(ddp, "world_size", None),
                )
            tracer.end_span(
                epoch_span,
                train_loss=float(train_loss),
                skipped_steps=epoch_skips,
            )
            epoch += 1
    except TrainingPreempted:
        raise  # emergency_stop already dumped the "preempt" recording
    except guard_lib.ReplicaDesync:
        if flight is not None:
            flight.dump("desync")
        raise
    except BaseException:
        if flight is not None:
            flight.dump("exception")
        raise
    finally:
        # An exception mid-epoch (preemption, NaN guard, a worker crash) must
        # not lose the trace — it is the post-mortem artifact — nor leave the
        # JSONL metrics record unflushed/truncated. The live plane tears
        # down too: endpoint closed, flight ring deregistered.
        if snap_engine is not None:
            snap_engine.close()
        tel.finish()
        stop_profiler()
        if tracer.enabled:
            # the causal artifact lands on EVERY exit path (clean drain,
            # preempt, crash): the typed summary goes into the history
            # stream before it closes, the Chrome trace next to it — spans
            # still open (an interrupted epoch) export flagged `open`
            metrics_writer.write(stamp("trace_summary", tracer.summary_record()))
            tracer.export()
        metrics_writer.close()
        if exporter is not None:
            exporter.stop()
        if flight is not None:
            flight_lib.uninstall(flight)

    if is_main:
        log(f"Finished Training on process {jax.process_index()}.")
    return state, history
