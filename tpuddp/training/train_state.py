"""TrainState: the one pytree that flows through the jitted train step.

Bundles what the reference keeps as four Python objects — model params (inside
``DDP(model)``), BatchNorm buffers, ``optim.Adam`` state, and the implicit
step/RNG bookkeeping — so the whole update is a single pure function
``(state, batch) -> state`` that XLA compiles once and keeps resident in HBM
(fixing quirk Q5: no per-batch host sync, SURVEY.md §3.5).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass
class TrainState:
    params: Any
    model_state: Any
    opt_state: Any
    step: jax.Array
    rng: jax.Array
    # Gradient-comm hook state (parallel/comm.py): the per-replica
    # error-feedback residual under comm_hook="bf16_ef" — a flat f32 vector
    # sharded over the data axis in shard_map mode, replicated in auto mode.
    # None (an empty pytree node: no leaf, no checkpoint entry) when the
    # configured hook carries no state, so every pre-existing TrainState
    # construction and checkpoint stays byte-identical.
    comm_state: Any = None
    # Numerical-guard skip counters (resilience/guard.py): under
    # training.guard the non-finite-gradient firewall increments
    # {"total", "consecutive"} int32 scalars whenever it turns a poisoned
    # optimizer update into a bitwise no-op; the epoch driver reads them to
    # log skips and trigger rollback-to-last-good. None (no leaf, no
    # checkpoint entry) when the guard is off — same compatibility contract
    # as comm_state.
    skipped_steps: Any = None


jax.tree_util.register_dataclass(
    TrainState,
    data_fields=[
        "params", "model_state", "opt_state", "step", "rng", "comm_state",
        "skipped_steps",
    ],
    meta_fields=[],
)


def create_train_state(model, optimizer, key, sample_input) -> TrainState:
    """Initialize params/buffers/optimizer state from a sample input.

    The caller passes the *same* key on every process (tpuddp's analog of DDP's
    construction-time rank-0 parameter broadcast, multi-GPU-training-torch.py:245,
    is done in DistributedDataParallel.init_state via broadcast_one_to_all).
    """
    init_key, run_key = jax.random.split(key)
    params, model_state = model.init(init_key, sample_input)
    opt_state = optimizer.init(params)
    return TrainState(
        params=params,
        model_state=model_state,
        opt_state=opt_state,
        step=jnp.zeros((), jnp.int32),
        rng=run_key,
    )
