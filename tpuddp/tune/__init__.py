"""The autotuning plane — the advisor's ACTUATORS (schema v12).

tpuddp/observability/advisor.py is the read-only evidence engine; this
package turns its recommendations into verified changes:

- :mod:`tpuddp.tune.probe`  — A/B delta arithmetic + the schema-validated
  ``TUNE_r*.json`` report (predicted vs measured per rule, endorsement
  verdicts). tools/autotune.py is its CLI.
- :mod:`tpuddp.tune.online` — the fleet tuner: applies at most one
  endorsed knob change per job per cooldown through the controller's
  drain-and-relaunch contract, measures the post-change window from the
  job's own history, and reverts automatically when the measured delta
  regresses. Every action lands as a typed ``tune_action`` history event
  and a ``tpuddp_tune_*`` /metrics counter.
"""

from tpuddp.tune.probe import (  # noqa: F401
    HIGHER_BETTER,
    LOWER_BETTER,
    build_tune_report,
    delta_pct,
    endorse,
    make_result_row,
    next_tune_path,
)
from tpuddp.tune.online import (  # noqa: F401
    FleetTuner,
    TunePolicy,
    endorsed_rules_from_report,
)
