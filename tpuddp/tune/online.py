"""The online fleet tuner — advisor recommendations applied through the
drain-and-relaunch contract, with measured verification and auto-revert.

The shape mirrors the autoscaler (tpuddp/fleet/autoscale.py): a frozen
:class:`TunePolicy`, a stateful :class:`FleetTuner` whose decision function
is pure in (artifacts, internal state, now), and injectable edges (the
``advise``/``reader`` callables) so the whole policy matrix unit-tests
without processes or sockets. The controller calls
:meth:`FleetTuner.observe_and_decide` per running job per tick and applies
any decision by mutating the job supervisor's env
(``$TPUDDP_TUNE_OVERLAY``, tpuddp/config.py) and signalling a drain — the
child exits 75, the supervisor relaunches with the overlay, and the
resumed header carries ``run_meta.tuning`` provenance.

The contract, per job:

- **at most one knob change per cooldown** — and only rules ENDORSED by an
  offline A/B probe (``endorsed_rules``, usually
  :func:`endorsed_rules_from_report` over a ``TUNE_r*.json``), unless the
  tuner was explicitly built with ``endorsed_rules=None`` (trust-advisor
  mode, for controlled experiments);
- **post-change measurement** — after an apply, the tuner watches the
  job's own history rows appended SINCE the change and compares the judge
  metric against the pre-change baseline window;
- **revert-if-regressed** — a measured improvement below
  ``revert_threshold_pct`` restores the previous overlay through the same
  drain contract; the refuted rule is never retried on that job;
- **typed audit** — every apply/keep/revert lands as a ``tune_action``
  event row in the job's namespaced ``history.jsonl`` and moves the
  ``tpuddp_tune_*`` /metrics counters (:meth:`FleetTuner.export_source`).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
from typing import Callable, Dict, Iterable, List, Optional, Set

from tpuddp.observability import advisor as advisor_lib
from tpuddp.observability import schema as schema_lib
from tpuddp.tune import probe

logger = logging.getLogger("tpuddp")

# Which history row types carry each judge metric — the post-change window
# is measured from the job's OWN typed records, not a scrape, so the tuner
# works on any run dir the advisor works on.
ROW_METRIC_TYPES = {
    "samples_per_sec": ("epoch", "step_stats"),
    "step_time_ms_p50": ("epoch", "step_stats"),
    "throughput_rps": ("serving_stats",),
    "e2e_ms_p50": ("serving_stats",),
    "tokens_per_sec": ("decode_stats",),
    "itl_ms_p95": ("decode_stats",),
}
_DEFAULT_JUDGE = {"training": "samples_per_sec", "serving": "throughput_rps"}


def _read_records(run_dir: str) -> List[dict]:
    return advisor_lib.load_run(run_dir)["records"]


def endorsed_rules_from_report(path: str) -> Set[str]:
    """The rules a ``TUNE_r*.json`` artifact endorsed — the offline probe's
    verdict feeding the online tuner. Empty set on a missing/invalid file
    (no probe = nothing endorsed, the tuner stays inert)."""
    try:
        with open(path) as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return set()
    if not isinstance(payload, dict):
        return set()
    return {
        row["rule"]
        for row in payload.get("results") or []
        if isinstance(row, dict) and row.get("endorsed") is True
        and isinstance(row.get("rule"), str)
    }


@dataclasses.dataclass(frozen=True)
class TunePolicy:
    """The online tuner's knob table (README "Self-tuning").

    ``cooldown_s`` bounds the action rate per job (applies, keeps and
    reverts all arm it); ``baseline_rows``/``measure_rows`` size the
    pre/post windows of history rows the judge metric is averaged over;
    ``revert_threshold_pct`` is the measured-improvement floor below which
    an applied change is rolled back; ``min_improvement_pct`` is the
    advisor-prediction floor below which a recommendation is not worth a
    drain at all."""

    cooldown_s: float = 300.0
    baseline_rows: int = 3
    measure_rows: int = 2
    revert_threshold_pct: float = 0.0
    min_improvement_pct: float = 1.0

    def __post_init__(self):
        if self.cooldown_s < 0:
            raise ValueError(f"cooldown_s must be >= 0, got {self.cooldown_s}")
        if self.baseline_rows < 1:
            raise ValueError(
                f"baseline_rows must be >= 1, got {self.baseline_rows}"
            )
        if self.measure_rows < 1:
            raise ValueError(
                f"measure_rows must be >= 1, got {self.measure_rows}"
            )


class FleetTuner:
    """Per-job apply/measure/revert state around the advisor's rule table.

    ``endorsed_rules``: the allow-list of rules the offline probe endorsed
    (None = trust the advisor's predictions — explicit opt-in only).
    ``advise``/``reader`` are injectable for socket-free tests."""

    def __init__(
        self,
        policy: Optional[TunePolicy] = None,
        endorsed_rules: Optional[Iterable[str]] = None,
        advise: Callable[[str], dict] = advisor_lib.advise,
        reader: Callable[[str], List[dict]] = _read_records,
    ):
        self.policy = policy or TunePolicy()
        self.endorsed_rules = (
            None if endorsed_rules is None else set(endorsed_rules)
        )
        self.advise = advise
        self.reader = reader
        # name -> {"phase", "active" (decision), "n_records",
        #          "baseline_value", "judge_metric"}
        self._state: Dict[str, dict] = {}
        self._kept: Dict[str, dict] = {}          # name -> overlay sections
        self._applied_rules: Dict[str, Set[str]] = {}
        self._generation: Dict[str, int] = {}
        self._last_action: Dict[str, float] = {}
        self.counters = {"applied": 0, "reverted": 0, "kept": 0}
        self.actions: List[dict] = []  # audit trail (tests + CLI logging)

    # ------------------------------------------------------------ helpers --
    def _cooled(self, name: str, now: float) -> bool:
        last = self._last_action.get(name)
        return last is None or (now - last) >= self.policy.cooldown_s

    @staticmethod
    def _tail_value(
        records: List[dict], metric: str, rows: int
    ) -> Optional[float]:
        types = ROW_METRIC_TYPES.get(metric, ())
        vals = [
            float(r[metric])
            for r in records
            if r.get("type") in types
            and isinstance(r.get(metric), (int, float))
        ]
        if not vals:
            return None
        tail = vals[-rows:]
        return sum(tail) / len(tail)

    @staticmethod
    def _merge_sections(base: dict, extra: dict) -> dict:
        merged = {sec: dict(knobs) for sec, knobs in base.items()}
        for sec, knobs in extra.items():
            dst = merged.setdefault(sec, {})
            for knob, value in knobs.items():
                if isinstance(value, dict) and isinstance(dst.get(knob), dict):
                    dst[knob] = {**dst[knob], **value}
                else:
                    dst[knob] = value
        return merged

    def _overlay_env(self, name: str, sections: dict, rule: str,
                     generation: int) -> dict:
        """The ``$TPUDDP_TUNE_OVERLAY`` JSON value: config sections plus
        the provenance fields config.apply_tune_overlay stamps into
        ``run_meta.tuning``."""
        return {
            "source": "fleet",
            "rule": rule,
            "generation": generation,
            **sections,
        }

    # ------------------------------------------------------------- decide --
    def observe_and_decide(
        self, name: str, kind: str, run_dir: str, now: float
    ) -> Optional[dict]:
        """One tick for one job: a decision dict (action apply/keep/revert)
        or None. Pure in (artifacts, internal state, now) — the controller
        applies the decision and then calls :meth:`mark_applied`."""
        st = self._state.get(name)
        if st is not None and st["phase"] == "measuring":
            return self._decide_measuring(name, st, run_dir)
        return self._decide_idle(name, kind, run_dir, now)

    def _decide_measuring(
        self, name: str, st: dict, run_dir: str
    ) -> Optional[dict]:
        active = st["active"]
        metric = st["judge_metric"]
        records = self.reader(run_dir)
        post = records[st["n_records"]:]
        post_value = self._tail_value(post, metric, self.policy.measure_rows)
        n_post = sum(
            1 for r in post
            if r.get("type") in ROW_METRIC_TYPES.get(metric, ())
            and isinstance(r.get(metric), (int, float))
        )
        if post_value is None or n_post < self.policy.measure_rows:
            return None  # not enough post-change evidence yet — keep waiting
        measured = probe.delta_pct(metric, st["baseline_value"], post_value)
        if measured is None:
            return None
        base = {
            "job": name,
            "rule": active["rule"],
            "rule_class": active["rule_class"],
            "knob": active["knob"],
            "diff": active["diff"],
            "section": active.get("section") or "training",
            "generation": active["generation"],
            "predicted_delta_pct": active["predicted_delta_pct"],
            "judge_metric": metric,
            "baseline_value": st["baseline_value"],
            "measured_value": post_value,
            "measured_delta_pct": round(measured, 2),
        }
        if measured < self.policy.revert_threshold_pct:
            kept = self._kept.get(name) or {}
            return {
                **base,
                "action": "revert",
                "overlay_env": (
                    self._overlay_env(
                        name, kept, active["rule"], active["generation"]
                    )
                    if kept else None
                ),
                "why": (
                    f"measured {measured:+.2f}% on {metric} below revert "
                    f"threshold {self.policy.revert_threshold_pct:+.2f}% "
                    f"(predicted {active['predicted_delta_pct']:+.2f}%)"
                ),
            }
        return {
            **base,
            "action": "keep",
            "overlay_env": None,  # keep = env unchanged, no drain
            "why": (
                f"measured {measured:+.2f}% on {metric} (predicted "
                f"{active['predicted_delta_pct']:+.2f}%) — change endorsed "
                "online"
            ),
        }

    def _decide_idle(
        self, name: str, kind: str, run_dir: str, now: float
    ) -> Optional[dict]:
        if not self._cooled(name, now):
            return None
        try:
            report = self.advise(run_dir)
        except Exception as e:  # noqa: BLE001 — a torn run dir is "no data"
            logger.warning("tune: advise over %s failed: %s", run_dir, e)
            return None
        recs = report.get("recommendations") or []
        tried = self._applied_rules.get(name, set())
        candidates = [
            r for r in recs
            if r["rule"] not in tried
            and r["predicted_delta_pct"] >= self.policy.min_improvement_pct
            and (
                self.endorsed_rules is None
                or r["rule"] in self.endorsed_rules
            )
        ]
        if not candidates:
            return None
        top = candidates[0]
        metric = (
            top["metric"]
            if top["metric"] in ROW_METRIC_TYPES
            else _DEFAULT_JUDGE.get(kind, "samples_per_sec")
        )
        records = self.reader(run_dir)
        baseline = self._tail_value(
            records, metric, self.policy.baseline_rows
        )
        if baseline is None:
            # nothing to judge a change against — acting now would make the
            # revert contract unenforceable, so don't act at all
            return None
        generation = self._generation.get(name, 0) + 1
        sections = self._merge_sections(
            self._kept.get(name) or {}, advisor_lib.overlay_from([top])
        )
        return {
            "action": "apply",
            "job": name,
            "rule": top["rule"],
            "rule_class": top["rule_class"],
            "knob": top["knob"],
            "diff": top["diff"],
            "section": top.get("section") or "training",
            "generation": generation,
            "predicted_delta_pct": top["predicted_delta_pct"],
            "evidence": top["evidence"],
            "judge_metric": metric,
            "baseline_value": baseline,
            "n_records": len(records),
            "overlay_env": self._overlay_env(
                name, sections, top["rule"], generation
            ),
            "why": top["reason"],
        }

    # -------------------------------------------------------------- commit --
    def mark_applied(
        self, name: str, run_dir: str, decision: dict, now: float
    ) -> None:
        """The controller applied ``decision`` (env + drain where needed):
        advance state, arm the cooldown, bump counters, land the typed
        ``tune_action`` event in the job's namespaced history."""
        action = decision["action"]
        self._last_action[name] = now
        if action == "apply":
            self._generation[name] = decision["generation"]
            self._state[name] = {
                "phase": "measuring",
                "active": decision,
                "n_records": decision["n_records"],
                "baseline_value": decision["baseline_value"],
                "judge_metric": decision["judge_metric"],
            }
            self.counters["applied"] += 1
        elif action == "keep":
            self._kept[name] = self._merge_sections(
                self._kept.get(name) or {},
                advisor_lib.overlay_from([{
                    "section": decision.get("section") or "training",
                    "diff": decision["diff"],
                }]),
            )
            self._applied_rules.setdefault(name, set()).add(decision["rule"])
            self._state[name] = {"phase": "idle", "active": None}
            self.counters["kept"] += 1
        elif action == "revert":
            self._applied_rules.setdefault(name, set()).add(decision["rule"])
            self._state[name] = {"phase": "idle", "active": None}
            self.counters["reverted"] += 1
        else:
            raise ValueError(f"unknown tune action {action!r}")
        entry = {"t": now, **{
            k: decision.get(k)
            for k in ("action", "job", "rule", "knob", "generation",
                      "measured_delta_pct", "why")
        }}
        self.actions.append(entry)
        logger.warning(
            "tune: %s -> %s rule=%s gen=%s (%s)",
            name, action, decision["rule"], decision["generation"],
            decision.get("why"),
        )
        self._append_event(run_dir, decision, now)

    def _append_event(self, run_dir: str, decision: dict, now: float) -> None:
        """One ``tune_action`` event row in the job's namespaced history —
        best-effort (a vanished run dir must not take the control loop
        down), single atomic append."""
        record = schema_lib.stamp("event", {
            "event": "tune_action",
            "action": decision["action"],
            "job": decision["job"],
            "rule": decision["rule"],
            "rule_class": decision["rule_class"],
            "knob": decision["knob"],
            "diff": decision["diff"],
            "generation": decision["generation"],
            "predicted_delta_pct": decision.get("predicted_delta_pct"),
            "measured_delta_pct": decision.get("measured_delta_pct"),
            "judge_metric": decision.get("judge_metric"),
            "why": decision.get("why"),
        })
        path = os.path.join(run_dir, "history.jsonl")
        try:
            with open(path, "a") as f:
                f.write(json.dumps(record, sort_keys=True) + "\n")
        except OSError as e:
            logger.warning("tune: could not append tune_action to %s: %s",
                           path, e)

    # ------------------------------------------------------------- metrics --
    def export_source(self) -> dict:
        """The ``tpuddp_tune_*`` /metrics series (exporter source shape —
        observability/exporter.py gauge/counter dicts, built inline so this
        module stays importable without the exporter)."""
        measuring = sum(
            1 for st in self._state.values() if st.get("phase") == "measuring"
        )
        def _counter(value, help):
            return {"type": "counter", "help": help, "value": value}
        return {
            "tpuddp_tune_applied_total": _counter(
                self.counters["applied"],
                "knob changes applied through drain-and-relaunch",
            ),
            "tpuddp_tune_reverted_total": _counter(
                self.counters["reverted"],
                "applied knob changes rolled back on a measured regression",
            ),
            "tpuddp_tune_kept_total": _counter(
                self.counters["kept"],
                "applied knob changes endorsed by their post-change window",
            ),
            "tpuddp_tune_measuring": {
                "type": "gauge",
                "help": "jobs currently inside a post-change measurement "
                        "window",
                "value": measuring,
            },
        }
