"""A/B probe arithmetic + the ``TUNE_r*.json`` report (schema v12).

The advisor predicts; this module is where predictions meet measurement.
One sign convention everywhere: a delta is an **improvement percentage**
(positive = better). For higher-better metrics (throughput) that is the
raw relative change; for lower-better metrics (latencies, wire bytes,
sheds) it is the relative REDUCTION — so a predicted +50% on
``grad_comm_bytes`` and a measured +48% compare directly, and the
endorsement rule is one comparison: ``measured >= min_improvement``.

The honesty contract (enforced by ``schema.validate_tune_payload``): a rule
whose measured delta regresses ships ``endorsed: false`` in the artifact —
the probe REFUSES to endorse it, whatever the prediction promised. The
fleet tuner (tpuddp/tune/online.py) only acts on endorsed rules.
"""

from __future__ import annotations

import os
import re
from typing import Dict, List, Optional

from tpuddp.observability import schema as schema_lib

# Direction table for every metric the advisor predicts on or the probe
# measures (observability/advisor.py measure_run keys). A metric missing
# from BOTH sets cannot be judged — delta_pct returns None and the row
# ships unendorsed, never silently assumed a direction.
HIGHER_BETTER = frozenset({
    "samples_per_sec",
    "throughput_rps",
    "tokens_per_sec",
    "batch_occupancy",
})
LOWER_BETTER = frozenset({
    "step_time_ms_p50",
    "epoch_time_s",
    "host_stall_ms",
    "e2e_ms_p50",
    "itl_ms_p95",
    "shed",
    "snapshot_skipped_queue_full",
    "snapshot_write_s",
    "grad_comm_bytes",
    "grad_comm_bytes_inter_host",
})


def delta_pct(metric: str, baseline, tuned) -> Optional[float]:
    """Improvement percentage of ``tuned`` over ``baseline`` on ``metric``
    (positive = better), or None when it cannot be judged (missing value,
    unknown direction)."""
    if baseline is None or tuned is None:
        return None
    if metric not in HIGHER_BETTER and metric not in LOWER_BETTER:
        return None
    baseline = float(baseline)
    tuned = float(tuned)
    if baseline == 0.0:
        # zero baselines are common for count metrics (shed 0, skips 0):
        # staying at zero is neutral, leaving zero is a full regression /
        # improvement — a ratio against zero would be meaningless either way
        if tuned == baseline:
            return 0.0
        good = (tuned > 0) == (metric in HIGHER_BETTER)
        return 100.0 if good else -100.0
    change = (tuned - baseline) / abs(baseline) * 100.0
    return change if metric in HIGHER_BETTER else -change


def endorse(
    measured_delta_pct: Optional[float], min_improvement_pct: float = 0.0
) -> bool:
    """The endorsement verdict: measured, and not a regression. An
    unmeasurable delta is NOT endorsable — no data is not a pass."""
    return (
        measured_delta_pct is not None
        and measured_delta_pct >= min_improvement_pct
    )


def make_result_row(
    rec: dict,
    baseline_metrics: Dict[str, float],
    tuned_metrics: Dict[str, float],
    min_improvement_pct: float = 0.0,
) -> dict:
    """One TUNE_r*.json result row from an advisor recommendation + the
    two measured metric dicts (advisor.measure_run of each run dir)."""
    metric = rec["metric"]
    baseline = baseline_metrics.get(metric)
    tuned = tuned_metrics.get(metric)
    measured = delta_pct(metric, baseline, tuned)
    return {
        "rule": rec["rule"],
        "rule_class": rec["rule_class"],
        "knob": rec["knob"],
        "diff": rec["diff"],
        "metric": metric,
        "predicted_delta_pct": rec["predicted_delta_pct"],
        "measured_delta_pct": (
            round(measured, 2) if measured is not None else None
        ),
        "baseline_value": baseline,
        "tuned_value": tuned,
        "endorsed": endorse(measured, min_improvement_pct),
        "evidence": rec["evidence"],
        "reason": rec.get("reason"),
    }


def build_tune_report(
    *,
    device: Optional[str],
    mode: str,
    baseline_metrics: Dict[str, float],
    results: List[dict],
    extra: Optional[dict] = None,
) -> dict:
    """Assemble + validate the tune_report payload; raises ValueError on a
    payload that would not survive ``tpuddp_inspect --validate`` (the writer
    must never ship an artifact its own reader rejects)."""
    payload = schema_lib.stamp("tune_report", {
        "device": device,
        "mode": mode,
        "baseline_metrics": dict(baseline_metrics),
        "results": list(results),
        **(extra or {}),
    })
    errors = schema_lib.validate_tune_payload(payload)
    if errors:
        raise ValueError(
            "refusing to write an invalid tune report: " + "; ".join(errors)
        )
    return payload


_TUNE_NAME_RE = re.compile(r"^TUNE_r(\d+)\.json$")


def next_tune_path(root: str) -> str:
    """Next free ``TUNE_rNN.json`` path under ``root`` (r01, r02, ...) —
    the BENCH_r*/SERVING_r* artifact-family naming."""
    highest = 0
    try:
        names = os.listdir(root)
    except OSError:
        names = []
    for name in names:
        m = _TUNE_NAME_RE.match(name)
        if m:
            highest = max(highest, int(m.group(1)))
    return os.path.join(root, f"TUNE_r{highest + 1:02d}.json")
