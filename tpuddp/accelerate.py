"""Accelerator — the managed two-level-API facade (SURVEY.md §2b #15).

Mirrors the HuggingFace ``Accelerator`` surface the reference's second
entrypoint uses (multi-GPU-training-accelerate.py:115-131,53,96,104-108):
``prepare``, ``backward``, ``device``, ``is_local_main_process``,
``is_main_process``, ``wait_for_everyone``, ``save_model``, ``gather`` — and
routes every one of them through the SAME mesh/collectives backend as the
explicit DistributedDataParallel API (the two-level contract of SURVEY.md §1).

JAX is functional, so the torch-imperative sequence

    outputs = model(inputs)          # forward
    loss = criterion(outputs, labels)
    accelerator.backward(loss)       # backward + grad sync
    optimizer.step()                 # param update

is bridged lazily: ``model(inputs)`` returns a :class:`LazyForward` and
``criterion(...)`` a :class:`LazyLoss`; nothing runs until
``accelerator.backward(loss)``, which executes ONE jitted global-batch
value_and_grad over the data-sharded mesh (gradient cross-replica reduction
falls out of XLA's data flow — the managed analog of DDP's allreduce),
stashes the averaged grads, and caches the loss value so a later
``loss.item()`` is free. ``optimizer.step()`` then applies the native
optimizer update. ``zero_grad()`` is the traditional no-op.

Managed-mode BatchNorm note: batch statistics are computed over the *global*
sharded batch under jit, i.e. SyncBatchNorm semantics by construction — the
behavior the reference README recommends turning on (README.md:79-81).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpuddp import optim as optim_lib
from tpuddp import seeding
from tpuddp.data.loader import DataLoader, ShardedDataLoader
from tpuddp.nn.core import Context, Module
from tpuddp.parallel import collectives as col
from tpuddp.parallel import comm as comm_lib
from tpuddp.parallel.mesh import data_mesh, replicate, shard_batch
from tpuddp.resilience import guard as guard_lib
from tpuddp.training import checkpoint as ckpt
from tpuddp.utils import batching


class LazyForward:
    """Deferred forward pass: records (model, inputs); materializes on demand."""

    def __init__(self, model: "PreparedModel", x):
        self._model = model
        self._x = x
        self._logits = None
        self._weights = None  # sample weights bound by a criterion, if any

    # hook consumed by tpuddp criterions (see nn/loss.py)
    def _tpuddp_bind_loss(self, criterion, labels, weights=None):
        # remember the batch weights so a train-mode materialization of THIS
        # forward masks padded rows out of BatchNorm statistics, same as the
        # grad/fused/scan steps do
        self._weights = weights
        return LazyLoss(self, criterion, labels, weights)

    @property
    def value(self):
        if self._logits is None:
            self._logits = self._model._forward_concrete(self._x, self._weights)
        return self._logits

    def __array__(self, dtype=None):
        arr = np.asarray(self.value)
        return arr.astype(dtype) if dtype is not None else arr

    def argmax(self, axis=-1):
        return jnp.argmax(self.value, axis=axis)


class LazyLoss:
    """Deferred loss: executed by ``Accelerator.backward`` (fused fwd+bwd) or
    by ``.item()`` (forward only, e.g. in eval loops)."""

    def __init__(self, fwd: LazyForward, criterion, labels, weights):
        self._fwd = fwd
        self._criterion = criterion
        self._labels = labels
        self._weights = weights
        self._value = None
        self._backward_requested = False
        self._dropped = False  # backward request superseded/cleared unexecuted
        self._drop_reason = (
            "a second accelerator.backward() or zero_grad() preceded "
            "optimizer.step()"
        )
        self._queued_on = None  # PreparedOptimizer holding this in a fuse queue
        self._value_src = None  # (losses_array, i) from a fused-scan flush

    def _run_backward(self):
        model = self._fwd._model
        self._backward_requested = True
        model._begin_backward(
            self._fwd._x, self._labels, self._weights, self._criterion, self
        )

    def device_value(self):
        """The loss as a device scalar with NO host sync — the deferred-metrics
        accumulator primitive (quirk Q5: ``loss.item()`` per batch is the
        reference's per-batch device sync; this is the opt-out)."""
        if self._value is None and self._queued_on is not None:
            # this loss sits in a fuse_steps queue: execute the queued steps
            # (one scan dispatch), which assigns every queued loss's value
            self._queued_on.flush()
        if self._value is None and self._value_src is not None:
            # lazily slice out of the flush's (K,) loss stack — only losses
            # actually read cost a dispatch (sum_losses never takes this path)
            arr, i = self._value_src
            self._value = arr[i]
        if self._value is None:
            model = self._fwd._model
            if model._pending is not None and model._pending[-1] is self:
                # backward was requested but step() hasn't fused it yet:
                # materialize grads + loss now (grad-only program)
                model._materialize_grads()
        if self._value is None and self._dropped:
            # The pending backward was superseded (second backward before
            # step()) or cleared (zero_grad); a recompute here would use the
            # CURRENT params and a fresh RNG key and silently return a value
            # different from the loss that was requested — refuse instead.
            raise RuntimeError(
                "this loss's backward request was dropped before it executed "
                f"({self._drop_reason}); its value was never computed."
            )
        if self._value is None:
            # forward-only path (no backward requested, e.g. eval loops)
            logits = jnp.asarray(self._fwd.value)
            self._value = self._criterion(
                logits, jnp.asarray(self._labels), self._weights
            )
        return self._value

    def item(self) -> float:
        return float(self.device_value())

    def __float__(self):
        return self.item()


def sum_losses(losses, initial=None):
    """Epoch-end device sum of many :class:`LazyLoss` values with the fewest
    device ops: losses that came out of the same fused-scan flush share one
    ``(K,)`` loss array and are summed array-at-a-time (two ops per flush)
    instead of scalar-at-a-time (two ops per batch — measured to dominate the
    steps themselves on dispatch-latency-bound runtimes). Returns a device
    scalar (0.0 for an empty sequence); ``float()`` it for the host value.
    ``initial`` seeds the sum — an exact mid-epoch resume carries the
    interrupted run's partial loss total through it."""
    import jax.numpy as _jnp

    losses = list(losses)
    if not losses:
        return _jnp.asarray(0.0 if initial is None else initial)
    for l in losses:
        if l._value is None and l._queued_on is not None:
            l._queued_on.flush()  # one flush settles every queued loss
    total = None if initial is None else _jnp.asarray(initial)
    by_stack = {}  # id(array) -> [array, [indices]]
    for l in losses:
        if l._value is None and l._value_src is not None:
            arr, i = l._value_src
            by_stack.setdefault(id(arr), [arr, []])[1].append(i)
        else:
            v = l.device_value()
            total = v if total is None else total + v
    for arr, idxs in by_stack.values():
        s = _jnp.sum(arr) if len(idxs) == arr.shape[0] else _jnp.sum(arr[_jnp.asarray(idxs)])
        total = s if total is None else total + s
    return total


class StagedUploadLoader:
    """Upload lookahead for the managed loop: issues batch N+1's host->device
    transfer (``jnp.asarray`` of the input tensor) before batch N is yielded,
    so the transfer rides the runtime's async stream while batch N's step is
    still recording/executing — the managed analog of the native epoch
    driver's staged chunks (training/loop.py). Yields ``(x_on_device, y, w)``
    with values and order unchanged; labels/weights stay host-side (they are
    small and the train step re-shards them anyway)."""

    def __init__(self, loader):
        self.loader = loader

    def set_epoch(self, epoch: int) -> None:
        self.loader.set_epoch(epoch)

    def __len__(self) -> int:
        return len(self.loader)

    def __getattr__(self, name):
        return getattr(self.loader, name)

    def __iter__(self):
        # multi-host shard_batch consumes process-local HOST data (its
        # make_array_from_process_local_data branch would round-trip a device
        # array back through np.asarray), so staging only helps — and only
        # runs — on single-process worlds
        put = jnp.asarray if jax.process_count() == 1 else (lambda a: a)
        prev = None
        for x, y, w in self.loader:
            cur = (put(x), y, w)  # issue the upload one batch early
            if prev is not None:
                yield prev
            prev = cur
        if prev is not None:
            yield prev


class FusedEvaluator:
    """One-dispatch-per-K-batches managed eval — the managed analog of the
    native ``build_eval_scan_step``. The facade eval loop costs 2+ dispatches
    per test batch (transform, forward) plus per-batch metric ops; this
    accumulator queues K batches and runs transform + forward + loss +
    correct/count accumulation as ONE jitted scan, carrying the running
    ``(loss_sum, correct, n)`` device scalars through the program so no
    eager per-batch arithmetic is dispatched at all.

    Reference semantics preserved (quirk Q3, multi-GPU-training-accelerate.py
    :60-75): every process evaluates the FULL unsharded test stream, the loss
    totalled is the per-batch criterion mean, and padded rows (w == 0) are
    excluded from both correctness counts and the criterion's weighting.

    Usage::

        ev = FusedEvaluator(model, criterion, transform=eval_transform)
        for x, y, w in test_loader:
            ev.add(x, y, w)
        loss_sum, correct, total = ev.finalize()
    """

    def __init__(self, model: "PreparedModel", criterion, transform=None,
                 fuse_steps=None, stage_uploads: bool = True):
        self.model = model
        self.criterion = criterion
        self.transform = transform
        # async-pipeline eval staging: each add()'d batch's host->device
        # transfer is issued IMMEDIATELY (device_put is async), so chunk
        # N+1's upload overlaps chunk N's scan dispatch instead of paying
        # K serial transfers at flush time. Single-process only: the
        # multi-host flush replicates process-local HOST data. Values and
        # order are unchanged — bitwise-identical metrics, ragged tails
        # included (tests/test_pipeline.py).
        self.stage_uploads = bool(stage_uploads) and jax.process_count() == 1
        # None = resolved at first use (flat 32, capped by the staging
        # budget over the batch bytes — the same policy as the train-side
        # fuse auto; see _resolve_auto_fuse)
        self.fuse_steps = None if fuse_steps is None else max(1, int(fuse_steps))
        self._queue = []
        self._stats = None
        self._progs = {}
        # auto-depth cache, keyed by the queued batch's shape_key: on ragged
        # streams the depth is RE-derived (and re-capped by the staging
        # budget) whenever the batch shape changes — a depth pinned by an
        # early small batch must not let a later large batch stage
        # depth x batch bytes past the ~256 MB budget
        self._fuse_cache = None  # (shape_key, resolved depth)

    def _resolve_fuse(self) -> int:
        if self.fuse_steps is not None:
            return self.fuse_steps
        batch_nbytes = None
        shape_key = None
        if self._queue:
            # .nbytes is metadata on both numpy and jax arrays — never
            # np.asarray a queued x here, it may be a staged device array
            # and the conversion would force a host transfer
            shape_key = self._queue[0][0]
            batch_nbytes = getattr(self._queue[0][1], "nbytes", None)
        params = self.model._params
        if params is None or params is _LOST_TO_FAILED_FLUSH or not self._queue:
            # don't cache while the model is unresolved OR before a real
            # batch is in hand (an empty-queue probe would pin the uncapped
            # depth and bypass the staging budget for the evaluator's life)
            return _resolve_auto_fuse(None, batch_nbytes)
        if self._fuse_cache is None or self._fuse_cache[0] != shape_key:
            self._fuse_cache = (
                shape_key, _resolve_auto_fuse(params, batch_nbytes)
            )
        return self._fuse_cache[1]

    def add(self, x, y, w=None):
        if w is None:
            w = np.ones(len(y), np.float32)
        # metadata-only key (shared with serving's scheduler): x may be a
        # staged device array and np.asarray on it would force a transfer
        shape_key = batching.shape_key(x)
        if self._queue and self._queue[0][0] != shape_key:
            self._flush()  # ragged stream: never stack mixed shapes
        if self.stage_uploads:
            # issue this batch's upload now, overlapping the previous
            # flush's in-flight dispatch (no-op for already-device arrays)
            x, y, w = jnp.asarray(x), jnp.asarray(y), jnp.asarray(w)
        self._queue.append((shape_key, x, y, w))
        if len(self._queue) >= self._resolve_fuse():
            self._flush()

    def _get_prog(self, k: int):
        if k not in self._progs:
            module, criterion, transform = (
                self.model.module, self.criterion, self.transform,
            )

            def prog(params, mstate, stats, xs, ys, ws):
                stacked = (jnp.stack(xs), jnp.stack(ys), jnp.stack(ws))

                def body(carry, inp):
                    x, y, w = inp
                    if transform is not None:
                        x = transform(x)
                    ctx = Context(train=False, rng=jax.random.key(0), axis_name=None)
                    logits, _ = module.apply(params, mstate, x, ctx)
                    loss = criterion(logits, y, w)
                    pred = jnp.argmax(logits, axis=-1)
                    mask = w > 0
                    # counts carry as int32 — f32 accumulation silently stops
                    # incrementing past 2^24 on long eval streams
                    correct = jnp.sum(
                        jnp.where(mask, pred == jnp.asarray(y), False).astype(jnp.int32)
                    )
                    n = jnp.sum(mask.astype(jnp.int32))
                    l0, c0, n0 = carry
                    return (l0 + loss, c0 + correct, n0 + n), None

                out, _ = jax.lax.scan(body, stats, stacked)
                return out

            self._progs[k] = jax.jit(prog)
        return self._progs[k]

    def _flush(self):
        queue, self._queue = self._queue, []
        if not queue:
            return
        model = self.model
        model._flush_queues()  # queued train updates must land first
        model._check_not_lost()
        if model._params is None:
            raise RuntimeError(
                "FusedEvaluator needs an initialized model: run one forward "
                "or a training step before evaluating"
            )
        if self._stats is None:
            stats = (
                jnp.zeros((), jnp.float32),  # loss sum
                jnp.zeros((), jnp.int32),    # correct
                jnp.zeros((), jnp.int32),    # weighted row count
            )
            if jax.process_count() > 1:
                # the global-mesh jit below needs global arrays for EVERY
                # input; the carried stats are global from the first flush's
                # output onward, but these initial zeros must be placed too
                stats = replicate(model.accelerator.mesh, stats)
            self._stats = stats
        fn = self._get_prog(len(queue))
        xs = tuple(jnp.asarray(e[1]) for e in queue)
        ys = tuple(jnp.asarray(e[2]) for e in queue)
        ws = tuple(jnp.asarray(e[3]) for e in queue)
        if jax.process_count() > 1:
            # multi-host: the jit over the global mesh needs global arrays;
            # every process holds the same full test batch (quirk Q3), so
            # replication is well-defined (same invariant as
            # PreparedModel._forward_concrete)
            xs, ys, ws = replicate(model.accelerator.mesh, (xs, ys, ws))
        self._stats = fn(model._params, model._model_state, self._stats, xs, ys, ws)

    def finalize(self):
        """Flush the remainder and fetch once. Returns host
        ``(loss_sum, correct, total)``."""
        self._flush()
        if self._stats is None:
            return 0.0, 0, 0
        sums = jax.device_get(self._stats)
        self._stats = None
        return float(sums[0]), int(sums[1]), int(sums[2])


class _FlatShardedUpdate(optim_lib.Optimizer):
    """GSPMD weight-update sharding for the managed path (the jit/auto analog
    of the shard_map path's explicit reduce-scatter/all-gather —
    arxiv.org/abs/2004.13336, ZeRO-1): presents the wrapped optimizer's
    tree-pytree API while storing its state as ONE flat padded f32 vector
    whose sharding is constrained over the data axis. Under ``jit``, XLA's
    partitioner then computes each parameter-shard's update on the chip that
    owns the moment shard, without any explicit collective in the program.
    The sharded STORAGE and partitioned update math are guaranteed (layout
    asserted in tests); the concrete collective the partitioner derives for
    the gradient exchange is backend-dependent (the TPU partitioner forms
    reduce-scatter for this pattern; the CPU test backend emits
    all-reduce + gather). The native shard_map path spells the
    reduce-scatter/all-gather out explicitly — and its compiled HLO is
    asserted to contain exactly that exchange
    (tests/test_weight_update_sharding.py)."""

    def __init__(self, inner, spec, mesh):
        from tpuddp.parallel.mesh import data_sharded, replicated as rep_sharding

        self.inner = inner
        self.spec = spec
        self.mesh = mesh
        self._sharded = data_sharded(mesh)
        self._replicated = rep_sharding(mesh)

    def _is_vec(self, leaf) -> bool:
        shape = getattr(leaf, "shape", None)
        return shape is not None and len(shape) == 1 and shape[0] == self.spec.total

    def init(self, params):
        """Create the flat state ALREADY sharded: jit with per-leaf
        out_shardings, so XLA materializes each chip's zero shard in place —
        no full-size single-device allocation, no host round trip."""
        def make():
            return self.inner.init(jnp.zeros((self.spec.total,), jnp.float32))

        shaped = jax.eval_shape(make)
        out_sh = jax.tree_util.tree_map(
            lambda l: self._sharded if self._is_vec(l) else self._replicated,
            shaped,
        )
        return jax.jit(make, out_shardings=out_sh)()

    def place_state(self, opt_state):
        """Lay a HOST-side flat state (a checkpoint restore) out over the
        mesh: (total,) vectors sharded over the data axis, scalars
        replicated (via the multi-host-safe replicate helper)."""
        def place(leaf):
            if self._is_vec(leaf):
                host = np.asarray(leaf)
                return jax.make_array_from_callback(
                    host.shape, self._sharded, lambda idx: host[idx]
                )
            return replicate(self.mesh, leaf)

        return jax.tree_util.tree_map(place, opt_state)

    def update(self, grads, opt_state, params):
        from jax.lax import with_sharding_constraint as wsc

        from tpuddp.training.step import _tree_to_vec, _vec_to_tree

        g_vec = wsc(_tree_to_vec(grads, self.spec), self._sharded)
        p_vec = _tree_to_vec(params, self.spec)
        update_flat = getattr(self.inner, "update_flat", None)
        if update_flat is not None:
            # LARS/LAMB: per-layer trust ratios over the spec's leaf
            # boundaries — the full vector is logically in hand here (XLA
            # partitions the segment sums), so no explicit collective
            new_p_vec, new_os = update_flat(
                g_vec, opt_state, p_vec, spec=self.spec
            )
        else:
            new_p_vec, new_os = self.inner.update(g_vec, opt_state, p_vec)
        # pin the state sharded (stable layout across steps/donation) and the
        # params replicated (the all-gather point)
        new_os = jax.tree_util.tree_map(
            lambda l: wsc(l, self._sharded) if self._is_vec(l) else l, new_os
        )
        new_p_vec = wsc(new_p_vec, self._replicated)
        return _vec_to_tree(new_p_vec, self.spec), new_os


def _resolve_auto_fuse(params, batch_nbytes=None) -> int:
    """The managed auto fusion depth: 32, capped by the SAME ~256 MB
    staged-bytes budget as the native ``scan_steps: auto``
    (training/loop.py) when the per-batch input bytes are known — the queue
    holds K device batches before each flush, so depth × batch bytes is
    real HBM. Shared by the train-side fuse_steps="auto" and the
    FusedEvaluator so the two can't drift apart.

    Big models used a shallower flat 8 through r4 (per-batch sharded
    placement flattens the scaling), but the r5 full-bench managed-AlexNet
    row measured fuse=32 within 2.9% of the native K-fused step, and the
    tunnel's per-dispatch RTT swings up to ~240 ms between sessions — depth
    is the amortization lever (BASELINE.md "Dispatch-RTT variance").
    ``params`` stays in the signature as the size hook should the policy
    become size-keyed again. The budget-cap arithmetic is the shared
    implementation in ``tpuddp/utils/batching.py`` (one policy for eval
    fusion, managed train fusion, and serving's device queues)."""
    del params
    return batching.resolve_fuse(batch_nbytes, cap=32)


# fold_in tag deriving the in-step augmentation key from the step's base rng:
# the dropout stream (Context rng) stays byte-identical whether augment is
# folded into the step or not
_AUG_FOLD = 0x617567  # "aug"


def _apply_step_augment(aug, rng, x):
    """On-device augmentation inside the compiled train step (the async
    pipeline's 'host workers only decode and stack' contract for the managed
    path): keyed off a fold of the step rng so the flip decisions are
    per-step deterministic and the model's own rng stream is untouched."""
    if aug is None:
        return x
    return aug(jax.random.fold_in(rng, _AUG_FOLD), x)


class _LostState:
    """Sentinel for model variables whose device buffers were donated to a
    fused dispatch that then failed — any read must fail loudly."""

    def __repr__(self):  # pragma: no cover - debugging aid
        return "<model state lost to a failed fused dispatch>"


_LOST_TO_FAILED_FLUSH = _LostState()


class PreparedModel:
    """The managed model: owns params/buffers, a compiled sharded train
    grad-step, and compiled replicated inference forwards. Mode toggles
    (``train()``/``eval()``) mirror ``nn.Module`` semantics."""

    def __init__(self, accelerator: "Accelerator", module: Module):
        self.accelerator = accelerator
        self.module = module
        self._params = None
        self._model_state = None
        self._training = True
        self._grad_step = None
        self._fused_step = None
        self._fused_scans = {}
        self._fwd = {}
        self._pending = None  # (x, y, w, criterion, step_idx, LazyLoss)
        self._pending_grads = None
        # model_state as of BEFORE the last grad-only forward: the guard's
        # skip branch reverts to it so a poisoned forward's BatchNorm stats
        # never outlive a skipped update (grad-only programs commit
        # _model_state eagerly, unlike the fused step whose cond owns it)
        self._mstate_before = None
        self._ones = {}  # cached sharded all-ones weight vectors by length
        self._bwd_key = accelerator._next_key()  # base key; fold_in(step) per batch
        self._bwd_counter = 0

    # -- torch-parity mode switches --
    def train(self):
        self._training = True
        return self

    def eval(self):
        self._training = False
        return self

    # Reading the variables flushes any queued fused steps first — a direct
    # `model.params` read (weight-norm logging, accelerator.gather) must
    # never see values that are K queued updates stale. Internal code that
    # runs *during* a flush touches `_params` directly (the queue is popped
    # at flush entry, so the re-entrant flush callback is a no-op, but
    # skipping the property keeps the hot path cheap).
    def _check_not_lost(self):
        if self._params is _LOST_TO_FAILED_FLUSH:
            raise RuntimeError(
                "the model's device buffers were donated to a fused-step "
                "dispatch that failed mid-execution; the parameters no "
                "longer exist. Restore from a checkpoint "
                "(accelerator.load_model) before continuing."
            )

    @property
    def params(self):
        self._flush_queues()
        self._check_not_lost()
        return self._params

    @params.setter
    def params(self, value):
        self._params = value

    @property
    def model_state(self):
        self._flush_queues()
        self._check_not_lost()
        return self._model_state

    @model_state.setter
    def model_state(self, value):
        self._model_state = value

    def _guard_enabled(self) -> bool:
        g = getattr(self.accelerator, "guard", None)
        return bool(g is not None and g.enabled)

    def _ensure_init(self, x):
        if self._params is not None:  # backing field: must not flush the queue
            return
        # Pretrained fine-tune hook: a module carrying pre-loaded variables
        # (tpuddp.models.torch_import.load_pretrained_alexnet) starts from
        # them instead of a fresh init.
        preloaded = getattr(self.module, "_tpuddp_initial_variables", None)
        if preloaded is not None:
            params, mstate = preloaded
        else:
            key = self.accelerator._next_key()
            sample = jax.ShapeDtypeStruct(
                (1,) + tuple(np.shape(x))[1:], jnp.asarray(x[:1]).dtype
            )
            aug = getattr(self.accelerator, "augment", None)
            if aug is not None:
                # in-step augmentation: the module sees the POST-augment
                # shape/dtype (e.g. uint8 32x32 decoded batches resized to
                # the compute dtype at 224) — derive it abstractly, nothing
                # executes
                sample = jax.eval_shape(
                    lambda v: aug(jax.random.key(0), v), sample
                )
                sample = jax.ShapeDtypeStruct(sample.shape, sample.dtype)
            params, mstate = self.module.init(key, sample)
        params, mstate = col.broadcast_one_to_all((params, mstate))
        self.params, self.model_state = replicate(
            self.accelerator.mesh, (params, mstate)
        )
        if self._guard_enabled():
            # prepare-time desync audit (the managed analog of the DDP
            # wrap-time verify): every replica's copy of the just-placed
            # parameters must fingerprint identically before the first step
            guard_lib.audit_or_raise(
                self.accelerator.mesh, self._params, where="accelerator-prepare"
            )

    def __call__(self, x) -> LazyForward:
        self._ensure_init(x)
        return LazyForward(self, x)

    # -- concrete executions --
    def _maybe_clip(self, grads):
        clip = getattr(self.accelerator, "clip_grad_norm", None)
        if clip is None:
            return grads
        clipped, _ = optim_lib.clip_grad_norm_(grads, clip)
        return clipped

    def _flush_queues(self):
        """Execute any queued fused steps so ``params``/``model_state`` are
        current before they are read (forward, save, gather)."""
        cb = getattr(self, "_flush_cb", None)
        if cb is not None:
            cb()

    def _forward_concrete(self, x, w=None):
        """Replicated-batch forward (used for eval / output materialization).
        Unprepared eval loaders feed the FULL batch to every process — the
        reference's accelerate eval behavior (quirk Q3). In train mode the
        batch's sample weights (``w``, bound when a criterion was applied to
        this forward) mask padded rows out of BatchNorm batch statistics —
        consistent with the grad/fused/scan steps; a bare train-mode
        ``model(x)`` with no criterion has no weights and treats every row as
        real (the new model_state is discarded either way)."""
        self._flush_queues()  # queued updates must land before params are read
        self._check_not_lost()
        train = self._training
        has_w = train and w is not None
        key = (np.shape(x), train, has_w)
        if key not in self._fwd:
            if has_w:
                def fwd(params, mstate, xv, wv, rng):
                    ctx = Context(
                        train=True, rng=rng, axis_name=None, sample_weight=wv
                    )
                    logits, _ = self.module.apply(params, mstate, xv, ctx)
                    return logits
            else:
                def fwd(params, mstate, xv, rng):
                    ctx = Context(train=train, rng=rng, axis_name=None)
                    logits, _ = self.module.apply(params, mstate, xv, ctx)
                    return logits

            self._fwd[key] = jax.jit(fwd)
        rng = self.accelerator._next_key() if train else jax.random.key(0)
        xr = jnp.asarray(x)
        args = (xr,)
        if has_w:
            args = (xr, jnp.asarray(w))
        if jax.process_count() > 1:
            # multi-host: the jit needs a global array (a plain local array
            # cannot address remote devices); every process holds the same
            # full batch (quirk Q3), so replication is well-defined
            args = replicate(self.accelerator.mesh, args)
        # single-process: pass the local array straight in — the jit inserts
        # the (async) transfer itself; an eager replicate() here measured
        # ~670 ms/call through the tunneled runtime vs 0.2 ms for the
        # dispatch, and it sat on the per-batch facade eval path
        return self._fwd[key](self._params, self._model_state, *args, rng)

    def _get_grad_step(self, criterion):
        if self._grad_step is None or self._grad_step[0] is not criterion:
            aug = getattr(self.accelerator, "augment", None)

            def grad_step(params, mstate, base_rng, step_idx, x, y, w):
                rng = jax.random.fold_in(base_rng, step_idx)
                x = _apply_step_augment(aug, rng, x)

                def loss_fn(p):
                    # sample_weight masks padded rows out of BatchNorm
                    # statistics (see nn/norm.py), matching the native path
                    ctx = Context(
                        train=True, rng=rng, axis_name=None, sample_weight=w
                    )
                    logits, new_mstate = self.module.apply(p, mstate, x, ctx)
                    return criterion(logits, y, w), new_mstate

                (loss, new_mstate), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)
                return loss, grads, new_mstate

            self._grad_step = (criterion, jax.jit(grad_step))
        return self._grad_step[1]

    def _shard_xyw(self, x, y, w):
        mesh = self.accelerator.mesh
        xb, yb = shard_batch(mesh, (jnp.asarray(x), jnp.asarray(y)))
        if w is None:
            n = len(y)
            if n not in self._ones:
                self._ones[n] = shard_batch(mesh, np.ones(n, np.float32))
            wb = self._ones[n]
        else:
            wb = shard_batch(mesh, jnp.asarray(w))
        return xb, yb, wb

    def _begin_backward(self, x, y, w, criterion, lazy_loss):
        """Record the backward request (torch's ``loss.backward()`` moment).

        Execution is deferred so ``optimizer.step()`` can run forward +
        backward + update as ONE fused jit dispatch; if the loss value is
        needed first (``item()`` before ``step()``), ``_materialize_grads``
        runs the grad-only program instead. The per-batch RNG key is
        ``fold_in(backward_base, batch_index)`` computed INSIDE the jitted
        step — an eager ``jax.random.split`` per batch would be a device
        dispatch of its own (measured ~3 ms through a tunneled runtime)."""
        if self._pending is not None:
            if getattr(self.accelerator, "gradient_accumulation_steps", 1) > 1:
                raise RuntimeError(
                    "gradient accumulation requires optimizer.step() after "
                    "EACH accelerator.backward(): the step is accumulated, "
                    "not applied, until the cycle boundary — a second "
                    "backward here would silently drop the previous "
                    "micro-batch's gradient."
                )
            old = self._pending[-1]
            if old._value is None:
                old._dropped = True
        step_idx = self._bwd_counter
        self._bwd_counter += 1
        self._pending = (x, y, w, criterion, step_idx, lazy_loss)
        # truthy marker preserving the backward-before-step contract; real
        # grad arrays only materialize on the grad-only path
        self._pending_grads = self._pending

    def _materialize_grads(self):
        self._flush_queues()  # grads must differentiate the CURRENT params
        self._check_not_lost()
        x, y, w, criterion, step_idx, lazy_loss = self._pending
        xb, yb, wb = self._shard_xyw(x, y, w)
        fn = self._get_grad_step(criterion)
        loss, grads, new_mstate = fn(
            self._params, self._model_state, self._bwd_key, step_idx, xb, yb, wb
        )
        self._mstate_before = self._model_state
        self._model_state = new_mstate
        self._pending_grads = grads
        self._pending = None
        lazy_loss._value = loss

    def _comm_hook_name(self) -> str:
        return getattr(self.accelerator, "comm_hook", "none")

    def _comm_density(self) -> float:
        from tpuddp.parallel.comm import DEFAULT_TOPK_DENSITY

        return getattr(self.accelerator, "topk_density", DEFAULT_TOPK_DENSITY)

    def _get_fused_step(self, criterion, optimizer):
        key = (criterion, optimizer)
        if self._fused_step is None or self._fused_step[0] != key:
            hook = self._comm_hook_name()
            density = self._comm_density()
            guard_on = self._guard_enabled()
            aug = getattr(self.accelerator, "augment", None)

            def fused(
                params, mstate, opt_state, comm_state, skipped, base_rng,
                step_idx, x, y, w,
            ):
                rng = jax.random.fold_in(base_rng, step_idx)
                x = _apply_step_augment(aug, rng, x)

                def loss_fn(p):
                    # sample_weight masks padded rows out of BatchNorm
                    # statistics (see nn/norm.py), matching the native path
                    ctx = Context(
                        train=True, rng=rng, axis_name=None, sample_weight=w
                    )
                    logits, new_mstate = self.module.apply(p, mstate, x, ctx)
                    return criterion(logits, y, w), new_mstate

                (loss, new_mstate), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(params)

                def apply_all():
                    # comm hook (managed emulation, parallel/comm.py):
                    # quantize the aggregated gradient through the wire dtype
                    # with error feedback BEFORE the clip, matching the
                    # native step's reduce-then-clip order
                    g, cs = comm_lib.local_quantize(
                        grads, comm_state, hook, density=density
                    )
                    g = self._maybe_clip(g)
                    new_params, new_opt = optimizer.update(g, opt_state, params)
                    return new_params, new_mstate, new_opt, cs

                if not guard_on:
                    new_params, out_mstate, new_opt, cs = apply_all()
                    return loss, new_params, out_mstate, new_opt, cs, skipped
                # firewall (resilience/guard.py): the grads here ARE the
                # XLA-aggregated global-batch f32 gradient — checked before
                # quantization; a non-finite step is a bitwise no-op on
                # params / opt-state / EF-residual / module buffers
                ok = guard_lib.tree_all_finite(grads)
                new_params, out_mstate, new_opt, cs, new_skipped = jax.lax.cond(
                    ok,
                    lambda: apply_all() + (guard_lib.reset_consecutive(skipped),),
                    lambda: (params, mstate, opt_state, comm_state,
                             guard_lib.bump_skip_counters(skipped)),
                )
                return loss, new_params, out_mstate, new_opt, cs, new_skipped

            self._fused_step = (
                key,
                jax.jit(fused, donate_argnums=(0, 1, 2, 3)),
            )
        return self._fused_step[1]

    def _get_fused_scan_step(self, criterion, optimizer, k: int):
        """K queued train steps as ONE jit dispatch: the managed analog of the
        native path's ``build_train_scan_step``. Takes the K sharded batches as
        tuples of arrays (stacked *inside* jit — stacking device arrays on the
        host would force a transfer) and returns the K per-step losses as one
        device array."""
        key = (criterion, optimizer, k)
        if key not in self._fused_scans:
            hook = self._comm_hook_name()
            density = self._comm_density()
            guard_on = self._guard_enabled()
            aug = getattr(self.accelerator, "augment", None)

            def fused_scan(
                params, mstate, opt_state, comm_state, skipped, base_rng,
                idxs, xs, ys, ws,
            ):
                stacked = (
                    idxs,
                    jnp.stack(xs),
                    jnp.stack(ys),
                    jnp.stack(ws),
                )

                def body(carry, inp):
                    p, ms, os_, cs, sk = carry
                    idx, x, y, w = inp
                    rng = jax.random.fold_in(base_rng, idx)
                    x = _apply_step_augment(aug, rng, x)

                    def loss_fn(pp):
                        ctx = Context(
                            train=True, rng=rng, axis_name=None, sample_weight=w
                        )
                        logits, new_ms = self.module.apply(pp, ms, x, ctx)
                        return criterion(logits, y, w), new_ms

                    (loss, new_ms), grads = jax.value_and_grad(
                        loss_fn, has_aux=True
                    )(p)

                    def apply_all():
                        # comm hook: same quantize -> clip -> update order as
                        # the single fused step; the error-feedback residual
                        # rides in the scan carry
                        g, cs2 = comm_lib.local_quantize(
                            grads, cs, hook, density=density
                        )
                        g = self._maybe_clip(g)
                        new_p, new_os = optimizer.update(g, os_, p)
                        return new_p, new_ms, new_os, cs2

                    if not guard_on:
                        return apply_all() + (sk,), loss
                    # firewall: per-scanned-step verdict on the f32
                    # aggregated gradient, pre-quantization; the skip
                    # counters ride the carry with the residual
                    ok = guard_lib.tree_all_finite(grads)
                    new_carry = jax.lax.cond(
                        ok,
                        lambda: apply_all() + (guard_lib.reset_consecutive(sk),),
                        lambda: (p, ms, os_, cs,
                                 guard_lib.bump_skip_counters(sk)),
                    )
                    return new_carry, loss

                (p, ms, os_, cs, sk), losses = jax.lax.scan(
                    body, (params, mstate, opt_state, comm_state, skipped),
                    stacked,
                )
                return p, ms, os_, cs, sk, losses

            self._fused_scans[key] = jax.jit(
                fused_scan, donate_argnums=(0, 1, 2, 3)
            )
        return self._fused_scans[key]


class PreparedOptimizer:
    """Wraps a tpuddp optimizer; ``step()`` applies the grads stashed by the
    last ``accelerator.backward`` (torch call-order parity)."""

    def __init__(self, optimizer: optim_lib.Optimizer, model: PreparedModel):
        self.optimizer = optimizer
        self.model = model
        self.opt_state = None
        self._update = None
        # fuse_steps > 1: step() queues sharded pending steps here and runs
        # them K at a time as one lax.scan dispatch (flush())
        self._queue = []
        # this optimizer's resolved fusion depth ("auto" resolves per MODEL,
        # from its size, at the first step — a shared Accelerator may drive
        # models of very different sizes, each deserving its own depth)
        self._fuse = None
        # gradient_accumulation_steps > 1: running device-side grad sum
        self._accum_grads = None
        self._accum_count = 0
        self._tree_add = None
        # comm_hook="bf16_ef": the persistent error-feedback residual (a
        # pytree like the gradients); None for stateless hooks
        self._comm_state = None
        # numerical guard (resilience/guard.py): the firewall's skip
        # counters ({"total", "consecutive"} int32 device scalars, the
        # managed seat of TrainState.skipped_steps); None when guard is off
        self._skipped = None
        # model_state as of the START of the current accumulation cycle —
        # the guard revert target when the whole cycle is skipped (the
        # cycle is the atomic update unit, native-parity)
        self._cycle_mstate = None
        # analytic per-update gradient-comm wire bytes (the counter), known
        # once the model's parameters exist
        self.grad_comm_bytes_per_step = None

    def zero_grad(self):
        if self.model._pending is not None:
            old = self.model._pending[-1]
            if old._value is None and old._backward_requested:
                old._dropped = True
        self.model._pending_grads = None
        self.model._pending = None

    def _ensure_opt_state(self):
        """Lazy optimizer-state init. Under
        ``Accelerator(weight_update_sharding=True)`` the optimizer is wrapped
        in :class:`_FlatShardedUpdate` first, so the moments are created flat
        and laid out SHARDED over the data axis."""
        if self.opt_state is not None:
            return
        model = self.model
        acc = model.accelerator
        if getattr(acc, "weight_update_sharding", False):
            if not isinstance(self.optimizer, _FlatShardedUpdate):
                from tpuddp.training.step import make_flat_param_spec

                spec = make_flat_param_spec(model.params, acc.mesh.devices.size)
                self.optimizer = _FlatShardedUpdate(self.optimizer, spec, acc.mesh)
            self.opt_state = self.optimizer.init(model.params)  # born sharded
        else:
            self.opt_state = self.optimizer.init(model.params)
        hook = getattr(acc, "comm_hook", "none")
        if hook in comm_lib.EF_HOOKS and self._comm_state is None:
            # every EF hook (bf16_ef/int8_ef/topk_ef) carries the same
            # pytree-shaped residual on this path; scales are recomputed
            # per step, never state
            self._comm_state = replicate(
                acc.mesh, comm_lib.init_residual_tree(model._params)
            )
        if model._guard_enabled() and self._skipped is None:
            self._skipped = replicate(acc.mesh, guard_lib.init_skip_counters())
        self.grad_comm_bytes_per_step = comm_lib.comm_bytes_for_hook(
            model._params, acc.mesh.devices.size, hook,
            wus=getattr(acc, "weight_update_sharding", False),
            # the managed path quantizes the XLA-aggregated gradient — the
            # collective itself stays f32, and the counter says so
            wire=False,
        )

    def step(self):
        model = self.model
        model._check_not_lost()
        if model._pending_grads is None:
            raise RuntimeError(
                "optimizer.step() called without a preceding accelerator.backward(loss)"
            )
        self._ensure_opt_state()
        if model._pending is not None:
            x, y, w, criterion, step_idx, lazy_loss = model._pending
            model._pending = None
            model._pending_grads = None
            xb, yb, wb = model._shard_xyw(x, y, w)
            accum = getattr(model.accelerator, "gradient_accumulation_steps", 1)
            if accum > 1:
                # grad-only program per micro-batch; ONE averaged (and then
                # clipped) update every `accum` steps — identical to one step
                # on the concatenated batch when micro-batches are equal-size
                fng = model._get_grad_step(criterion)
                loss, grads, new_mstate = fng(
                    model._params, model._model_state,
                    model._bwd_key, step_idx, xb, yb, wb,
                )
                model._mstate_before = model._model_state
                model._model_state = new_mstate
                lazy_loss._value = loss
                self._accumulate(grads, accum)
                return
            fuse = self._fuse
            if fuse is None:
                fuse = getattr(model.accelerator, "fuse_steps", 1)
                if fuse == "auto":
                    # resolved once per optimizer, at the first step, when a
                    # real batch is in hand: flat 32 capped by the staging
                    # budget over THIS batch's bytes (the queue holds K such
                    # batches on device before each flush)
                    fuse = _resolve_auto_fuse(
                        model._params, getattr(xb, "nbytes", None)
                    )
                self._fuse = fuse
            if fuse > 1:
                # queue the sharded step; K of them run as ONE scan dispatch.
                # Reading params/loss values before the queue fills triggers
                # an early flush, so semantics never depend on the queue.
                if self._queue and (
                    self._queue[0][3] is not criterion
                    # ragged stream (e.g. a raw smaller last batch from an
                    # unprepared loader): never stack mixed shapes/dtypes —
                    # flush the homogeneous prefix first (jnp.stack would
                    # silently promote a mixed-dtype stack)
                    or self._queue[0][0].shape != xb.shape
                    or self._queue[0][0].dtype != xb.dtype
                ):
                    self.flush()
                self._queue.append((xb, yb, wb, criterion, step_idx, lazy_loss))
                lazy_loss._queued_on = self
                model._flush_cb = self.flush
                if len(self._queue) >= fuse:
                    self.flush()
                return
            self._run_fused(xb, yb, wb, criterion, step_idx, lazy_loss)
            return
        # grads were materialized early (loss.item() before step())
        grads = model._pending_grads
        model._pending_grads = None
        accum = getattr(model.accelerator, "gradient_accumulation_steps", 1)
        if accum > 1:
            # an early loss read must not bypass accumulation (an immediate
            # full-scale update here would be a silent 4x-LR bug)
            self._accumulate(grads, accum)
            return
        fn = self._get_apply_update()
        guard_on = model._guard_enabled()
        mstates = (
            (model._mstate_before, model._model_state) if guard_on else None
        )
        try:
            (model.params, self.opt_state, self._comm_state, self._skipped,
             mstate) = fn(
                grads, self.opt_state, model.params, self._comm_state,
                self._skipped, mstates, 1.0,
            )
        except BaseException:
            self._poison_if_donated()
            raise
        if guard_on:
            model._model_state = mstate

    def _accumulate(self, grads, accum: int):
        """Fold one micro-batch's gradient into the running device-side sum;
        apply ONE averaged (then clipped) update at the cycle boundary."""
        model = self.model
        if self._accum_grads is None:
            # cycle start: remember the buffers as of BEFORE this cycle's
            # first forward — the guard reverts a skipped cycle to them
            self._cycle_mstate = model._mstate_before
            self._accum_grads = grads
        else:
            if self._tree_add is None:
                self._tree_add = jax.jit(
                    lambda a, b: jax.tree_util.tree_map(jnp.add, a, b),
                    donate_argnums=(0,),
                )
            self._accum_grads = self._tree_add(self._accum_grads, grads)
        self._accum_count += 1
        if self._accum_count >= accum:
            self.flush_accumulation()

    def flush_accumulation(self):
        """Apply any partially-accumulated cycle now (averaged over the
        micro-batches actually seen) — the dataloader-end behavior of HF's
        ``accumulate()``. No-op when nothing is accumulated. Call at epoch
        end so a partial cycle neither leaks into the next epoch nor gets
        silently dropped at training end."""
        if self._accum_count == 0:
            return
        model = self.model
        fn = self._get_apply_update()
        guard_on = model._guard_enabled()
        mstates = (
            (self._cycle_mstate, model._model_state) if guard_on else None
        )
        try:
            (model._params, self.opt_state, self._comm_state, self._skipped,
             mstate) = fn(
                self._accum_grads, self.opt_state, model._params,
                self._comm_state, self._skipped, mstates,
                1.0 / self._accum_count,
            )
        except BaseException:
            self._poison_if_donated()
            raise
        if guard_on:
            model._model_state = mstate
        self._accum_grads = None
        self._accum_count = 0
        self._cycle_mstate = None

    def _get_apply_update(self):
        """Jitted scale -> comm hook -> clip -> optimizer.update (the hook and
        the clip always apply to the final, averaged gradient — never per
        micro-batch — matching the native cycle-boundary order). Under the
        guard, the finiteness verdict on the scaled f32 gradient (checked
        before quantization) gates the whole tail through ``lax.cond``."""
        if self._update is None:
            clip = getattr(self.model.accelerator, "clip_grad_norm", None)
            hook = self._comm_hook_name()
            density = self.model._comm_density()
            guard_on = self.model._guard_enabled()

            def apply(grads, opt_state, params, comm_state, skipped, mstates, scale):
                grads = jax.tree_util.tree_map(lambda g: g * scale, grads)

                def apply_all():
                    g, cs = comm_lib.local_quantize(
                        grads, comm_state, hook, density=density
                    )
                    if clip is not None:
                        g, _ = optim_lib.clip_grad_norm_(g, clip)
                    new_params, new_opt = self.optimizer.update(
                        g, opt_state, params
                    )
                    return new_params, new_opt, cs

                if not guard_on:
                    new_params, new_opt, cs = apply_all()
                    return new_params, new_opt, cs, skipped, mstates
                # mstates = (pre-cycle buffers, post-forward buffers): the
                # grad-only programs committed model_state eagerly, so the
                # skip branch must also hand the PRE-cycle buffers back —
                # a poisoned forward's BN stats die with the skipped update
                mstate0, mstate_now = mstates
                ok = guard_lib.tree_all_finite(grads)
                return jax.lax.cond(
                    ok,
                    lambda: apply_all()
                    + (guard_lib.reset_consecutive(skipped), mstate_now),
                    lambda: (params, opt_state, comm_state,
                             guard_lib.bump_skip_counters(skipped), mstate0),
                )

            self._update = jax.jit(apply, donate_argnums=(0, 1, 2, 3))
        return self._update

    def _comm_hook_name(self) -> str:
        return getattr(self.model.accelerator, "comm_hook", "none")

    def _poison_if_donated(self):
        """After a failed dispatch that may have donated the model/optimizer
        buffers: poison the model so reads raise the clear restore-from-
        checkpoint error, not JAX's obscure 'Array has been deleted'."""
        model = self.model
        leaves = jax.tree_util.tree_leaves(
            (model._params, model._model_state, self.opt_state,
             self._comm_state, self._skipped)
        )
        if any(getattr(l, "is_deleted", lambda: False)() for l in leaves):
            model._params = model._model_state = _LOST_TO_FAILED_FLUSH
            self.opt_state = None
            self._comm_state = None
            self._skipped = None

    def skip_counters(self):
        """Host ``(total, consecutive)`` of the guard's skipped-update
        counters; ``(0, 0)`` when the guard is off or nothing has stepped.
        One tiny fetch — call per epoch, not per step."""
        if self._skipped is None:
            return 0, 0
        t, c = jax.device_get(
            (self._skipped["total"], self._skipped["consecutive"])
        )
        return int(t), int(c)

    def _run_fused(self, xb, yb, wb, criterion, step_idx, lazy_loss):
        """forward + backward + optimizer update as ONE jit dispatch (the
        managed analog of the native compiled train step)."""
        model = self.model
        fn = model._get_fused_step(criterion, self.optimizer)
        try:
            loss, new_params, new_mstate, new_opt, new_comm, new_skipped = fn(
                model._params, model._model_state, self.opt_state,
                self._comm_state, self._skipped, model._bwd_key, step_idx,
                xb, yb, wb,
            )
        except BaseException:
            self._poison_if_donated()
            raise
        model._params, model._model_state = new_params, new_mstate
        self.opt_state = new_opt
        self._comm_state = new_comm
        self._skipped = new_skipped
        lazy_loss._value = loss

    def flush(self):
        """Run all queued steps now. K >= 2 entries run as one lax.scan
        program (compiled once per distinct K; the per-epoch remainder reuses
        the single-step program entry by entry)."""
        queue, self._queue = self._queue, []
        if not queue:
            return
        try:
            self._dispatch_flush(queue)
        except BaseException:
            # The dispatch failed (compile OOM, runtime disconnect): the
            # queued updates are lost and donated buffers may be gone. Make
            # every still-unresolved loss read fail loudly rather than
            # silently recompute a forward against un-updated params.
            model = self.model
            for entry in queue:
                lazy_loss = entry[5]
                lazy_loss._queued_on = None
                if lazy_loss._value is None and lazy_loss._value_src is None:
                    lazy_loss._dropped = True
                    lazy_loss._drop_reason = (
                        "its fused-step dispatch failed (see the original "
                        "exception)"
                    )
            # Donation only happens if execution started; a trace/compile
            # failure leaves the buffers valid.
            self._poison_if_donated()
            raise

    def _dispatch_flush(self, queue):
        model = self.model
        if len(queue) == 1:
            xb, yb, wb, criterion, step_idx, lazy_loss = queue[0]
            self._run_fused(xb, yb, wb, criterion, step_idx, lazy_loss)
            lazy_loss._queued_on = None
            return
        # Any multi-step queue — full, epoch remainder, or an early-read
        # partial — dispatches as ONE scan. Scan programs are cached per
        # length, and the lengths that occur recur (the full depth every
        # cycle, the same remainder every epoch), so each compiles once per
        # run; an epoch SHORTER than the fusion depth still gets exactly one
        # dispatch per epoch instead of silently degrading to per-step.
        criterion = queue[0][3]
        fn = model._get_fused_scan_step(criterion, self.optimizer, len(queue))
        idxs = jnp.asarray([e[4] for e in queue], jnp.int32)
        xs = tuple(e[0] for e in queue)
        ys = tuple(e[1] for e in queue)
        ws = tuple(e[2] for e in queue)
        new_params, new_mstate, new_opt, new_comm, new_skipped, losses = fn(
            model._params, model._model_state, self.opt_state,
            self._comm_state, self._skipped, model._bwd_key, idxs, xs, ys, ws,
        )
        model._params, model._model_state = new_params, new_mstate
        self.opt_state = new_opt
        self._comm_state = new_comm
        self._skipped = new_skipped
        for i, entry in enumerate(queue):
            lazy_loss = entry[5]
            lazy_loss._value_src = (losses, i)
            lazy_loss._queued_on = None


class Accelerator:
    """Managed entry to the tpuddp backend. Topology comes from the live JAX
    runtime (the analog of HF accelerate reading torchrun env vars)."""

    def __init__(
        self,
        mesh=None,
        seed: Optional[int] = None,
        fuse_steps: int = 1,
        num_chips: Optional[int] = None,
        clip_grad_norm: Optional[float] = None,
        gradient_accumulation_steps: int = 1,
        weight_update_sharding: bool = False,
        comm_hook: str = "none",
        bucket_cap_mb: float = comm_lib.DEFAULT_BUCKET_CAP_MB,
        comm_topology: str = "flat",
        topk_density: float = comm_lib.DEFAULT_TOPK_DENSITY,
        guard=None,
        augment=None,
        comm_overlap="auto",
    ):
        """``fuse_steps``: K > 1 batches per-step calls into one compiled
        lax.scan dispatch (the managed analog of the native scan fusion) —
        loss values then materialize at flush time, so pair it with deferred
        metric reading (collect the LazyLoss objects; read at epoch end).
        ``"auto"`` resolves at each optimizer's first step to 32 (the
        BASELINE-measured managed depth — the r5 full-bench managed-AlexNet
        row ran fuse=32 within ~3% of the native K-fused step), capped by a
        ~256 MB queued-batch staging budget computed from the actual batch's
        bytes (large inputs resolve shallower; e.g. 128x224x224x3 bf16
        batches cap at 6). The native ``scan_steps: auto`` analog goes
        deeper (64, same budget) because the native scan stages one
        super-batch instead of paying per-batch sharded placement.

        ``num_chips``: restrict the data mesh to the first N local devices
        (the managed analog of ``local.tpu.num_chips`` — without it a
        configured sub-world would be silently ignored on multi-chip hosts).
        Ignored when an explicit ``mesh`` is passed.

        ``weight_update_sharding``: ZeRO-1 on the managed path — Adam moments
        live as a flat vector SHARDED over the data axis and each chip
        computes only its parameter shard's update (XLA lowers the exchange
        to reduce-scatter + all-gather via sharding constraints; see
        :class:`_FlatShardedUpdate` and arxiv.org/abs/2004.13336).

        ``comm_hook``: gradient-communication hook ("none" | "bf16" |
        "bf16_ef"), the managed-path analog of torch DDP's comm hooks
        (parallel/comm.py). On this path XLA's partitioner inserts the
        cross-replica psum inside backward, so the hook quantizes the
        aggregated gradient through the wire dtype — with bf16_ef's
        persistent error-feedback residual (round-tripped by
        save_state/load_state) — preserving the hooks' convergence contract;
        the genuine on-the-wire byte reduction is the explicit
        (DistributedDataParallel, shard_map) path's property.
        ``bucket_cap_mb`` is accepted for knob parity (bucketing is a
        collective-granularity construct of the explicit path).

        ``guard``: the numerical guard (resilience/guard.py; same knob as
        ``DistributedDataParallel``): the fused/scan/accumulation update
        programs gate the optimizer tail behind a finiteness check on the
        XLA-aggregated f32 gradient (checked before the comm hook
        quantizes), a poisoned step is a bitwise no-op counted in the
        optimizer's skip counters (``PreparedOptimizer.skip_counters()``,
        round-tripped by save_state/load_state), and ``prepare`` audits
        every replica's parameter copy. Off by default — identical
        programs.

        ``augment``: on-device train augmentation ``(rng, x) -> x`` folded
        INTO the compiled step programs (the async pipeline's managed-path
        analog of the native ``DistributedDataParallel(augment=...)``):
        ``model(raw_inputs)`` then takes decoded uint8 batches and the
        normalize/flip/resize runs inside the same dispatch as forward+
        backward+update — one dispatch per step, host workers only decode
        and stack. The augment key derives from the step rng by a constant
        fold (``_AUG_FOLD``), so the model's own rng stream (dropout) is
        unchanged by folding. Train-grad programs only; eval paths keep
        their explicit transform. None (default): inputs are used as
        given — the legacy separate-augment cadence."""
        self.mesh = mesh if mesh is not None else data_mesh(num_chips)
        key, _ = seeding.set_seed_based_on_rank(base_seed=seed)
        self._key = key
        self._models = []
        if fuse_steps in (None, "auto"):
            self.fuse_steps = "auto"
        else:
            self.fuse_steps = max(1, int(fuse_steps))
        # clip the GLOBAL-batch gradient (already cross-replica aggregated
        # under jit) before the update — clip-after-aggregate semantics,
        # same as the native path's clip_grad_norm
        self.clip_grad_norm = (
            float(clip_grad_norm) if clip_grad_norm is not None else None
        )
        # HF-parity gradient accumulation: optimizer.step() accumulates the
        # global-batch gradient and applies ONE averaged update every N
        # steps (zero_grad stays safe to call every batch, as HF's managed
        # no-op semantics allow; the boundary step clears the accumulator).
        self.gradient_accumulation_steps = max(1, int(gradient_accumulation_steps))
        self.weight_update_sharding = bool(weight_update_sharding)
        self.comm_hook = comm_lib.validate_hook(comm_hook)
        # comm_topology is accepted for config parity with the explicit API,
        # but only "flat" is implementable here: the managed path's gradient
        # collective is inserted by XLA's partitioner, so there is no seam to
        # express the intra-host/inter-host hop split through. The knob
        # refuses rather than silently running flat under a hierarchical
        # label — the byte accounting must never claim a topology that did
        # not reach the wire.
        comm_lib.validate_topology(comm_topology)
        if comm_topology != "flat":
            raise ValueError(
                "comm_topology='hierarchical' needs the explicit API "
                "(DistributedDataParallel / train_native.py, mode="
                "'shard_map'): the managed path's collective is XLA-"
                "inserted and cannot be hop-split"
            )
        self.comm_topology = comm_topology
        self.topk_density = float(topk_density)
        comm_lib.bucket_topk(1, self.topk_density)  # range-validate eagerly
        self.guard = guard_lib.resolve_guard(guard)
        # comm_overlap is accepted for config parity with the explicit API,
        # but the managed path has no collective of its own to stage: XLA's
        # partitioner inserts the psum inside backward, so there is no seam
        # to issue per-segment collectives through. "auto"/False record the
        # disabled provenance; True refuses rather than silently running the
        # barrier program under an overlap label.
        from tpuddp.parallel.ddp import _normalize_overlap

        overlap = _normalize_overlap(comm_overlap)
        if overlap is True:
            raise ValueError(
                "comm_overlap=true needs the explicit API "
                "(DistributedDataParallel / train_native.py, mode="
                "'shard_map'): the managed path's collective is XLA-inserted "
                "and cannot be issued per backward segment"
            )
        self.comm_overlap_meta = {
            "enabled": False,
            "segments": None,
            "reason": (
                "disabled" if overlap is False else
                "managed path: the gradient collective is XLA-inserted, not "
                "issued per segment"
            ),
        }
        self.augment = augment
        # typed event dicts from the last load_state's elastic reshard (a
        # topology_change when the restored state was written on a different
        # world size); the managed entrypoint lands them in history.jsonl
        self.last_restore_events: list = []
        self.bucket_cap_mb = float(bucket_cap_mb)
        if self.bucket_cap_mb <= 0:
            # same knob contract as DistributedDataParallel: a config that
            # validates against one API must not crash the other
            raise ValueError(f"bucket_cap_mb must be > 0, got {bucket_cap_mb!r}")
        if self.gradient_accumulation_steps > 1:
            if self.fuse_steps == "auto":
                # accumulation owns the step cadence; auto-fusion yields
                self.fuse_steps = 1
            elif self.fuse_steps > 1:
                raise ValueError(
                    "gradient_accumulation_steps and fuse_steps are mutually "
                    "exclusive (fused scan steps each apply an update)"
                )

    # -- topology (HF property-name parity) --
    @property
    def device(self):
        return self.mesh.devices.flat[0]

    @property
    def num_processes(self) -> int:
        return jax.process_count()

    @property
    def process_index(self) -> int:
        return jax.process_index()

    @property
    def local_process_index(self) -> int:
        return jax.process_index()

    @property
    def is_main_process(self) -> bool:
        return jax.process_index() == 0

    @property
    def is_local_main_process(self) -> bool:
        return jax.process_index() == 0

    def _next_key(self):
        self._key, sub = jax.random.split(self._key)
        return sub

    def next_rng_key(self):
        """A fresh PRNG key from the accelerator's per-process stream (for
        host-driven augmentation in the managed path)."""
        return self._next_key()

    # -- the core verbs --
    def prepare(self, *objects):
        """Wrap (model, optimizer, dataloader) for distributed execution —
        reference usage at multi-GPU-training-accelerate.py:129-131. DataLoaders
        are re-created sharded (each process loads only its replicas' shard;
        batch_size stays per-replica, matching HF semantics and the README's
        memory caveat, README.md:72-73). Objects deliberately NOT prepared
        (the reference's test_loader) keep their full unsharded stream."""
        out = []
        model_ctx: Optional[PreparedModel] = None
        for obj in objects:
            if isinstance(obj, Module):
                model_ctx = PreparedModel(self, obj)
                self._models.append(model_ctx)
                out.append(model_ctx)
            elif isinstance(obj, PreparedModel):
                model_ctx = obj
                out.append(obj)
            elif isinstance(obj, optim_lib.Optimizer):
                out.append(("optimizer", obj))
            elif isinstance(obj, (DataLoader, ShardedDataLoader)):
                out.append(obj)
            else:
                raise TypeError(f"cannot prepare object of type {type(obj)!r}")
        # bind optimizers to the model prepared in the same call
        for i, obj in enumerate(out):
            if isinstance(obj, tuple) and obj[0] == "optimizer":
                if model_ctx is None:
                    raise ValueError("prepare() got an optimizer but no model")
                out[i] = PreparedOptimizer(obj[1], model_ctx)
                model_ctx._optimizer = out[i]  # for load_model's reset
        # A user-supplied sampler's order is PRESERVED: the sharded loader
        # pads it by wrap and strides it across replicas (HF semantics — a
        # custom sampler rides inside the sharded batch sampler; it is never
        # silently replaced with a reshuffle).
        out = [
            ShardedDataLoader(
                o.dataset, o.batch_size, self.mesh,
                shuffle=o.shuffle,
                seed=o.seed,
                drop_last=o.drop_last,
                sampler=o.sampler,
            )
            if isinstance(o, DataLoader)
            else o
            for o in out
        ]
        return out[0] if len(out) == 1 else tuple(out)

    def backward(self, loss: LazyLoss):
        """Fused forward+backward+grad-sync (reference :53's
        ``accelerator.backward(loss)``)."""
        if not isinstance(loss, LazyLoss):
            raise TypeError(
                "accelerator.backward expects the LazyLoss produced by a tpuddp "
                "criterion applied to a prepared model's outputs"
            )
        loss._run_backward()

    def wait_for_everyone(self):
        """Global barrier (reference :106)."""
        col.barrier("tpuddp_accelerate_wait")

    def save_model(self, model: PreparedModel, save_dir: str):
        """Single-writer save of the *unwrapped* weights (reference :108's
        ``accelerator.save_model`` contract): process 0 writes
        ``save_dir/model.npz``."""
        model._flush_queues()  # queued fused steps must land before the read
        if self.is_main_process:
            os.makedirs(save_dir, exist_ok=True)
            ckpt.save(
                os.path.join(save_dir, "model.npz"),
                {"params": model.params, "model_state": model.model_state},
            )
        col.barrier("tpuddp_accelerate_save")

    def load_model(self, model: PreparedModel, save_dir: str):
        """Restore the weights written by :meth:`save_model` into a prepared
        model (the managed resume path; the reference only documents loading,
        README.md:51-52). The model must have been initialized (one forward
        or a prior training step) so the checkpoint has a structure to load
        into."""
        # gradients/steps staged against the PRE-restore weights must not be
        # executed (a flush would) or applied on top of the restored ones
        self._discard_staged_work(model, "load_model discarded the staged step")
        if model._params is _LOST_TO_FAILED_FLUSH:
            raise RuntimeError(
                "this model's buffers were lost to a failed fused dispatch; "
                "re-prepare it (accelerator.prepare) and run one forward, "
                "then load_model"
            )
        if model._params is None:
            raise RuntimeError(
                "load_model needs an initialized model: run one forward "
                "(model(x)) first so the parameter structure exists"
            )
        restored = ckpt.load(
            os.path.join(save_dir, "model.npz"),
            {"params": model._params, "model_state": model._model_state},
        )
        model._params, model._model_state = replicate(
            self.mesh, (restored["params"], restored["model_state"])
        )
        opt = getattr(model, "_optimizer", None)
        if opt is not None:
            # Adam moments computed against the PRE-restore weights must not
            # steer updates to the restored ones; this is a weights-only
            # restore, so the moments re-init to zero on the next step.
            # load_state restores them losslessly. The comm-hook residual is
            # pre-restore compression error — it resets with them.
            opt.opt_state = None
            opt._comm_state = None
        return model

    @staticmethod
    def _discard_staged_work(model: PreparedModel, reason: str):
        """Drop anything staged against the CURRENT (about-to-be-replaced)
        weights — the pending backward, queued fused steps, and a partial
        accumulation cycle — so a restore never executes or applies them.
        Must run BEFORE any flush: a flush would *execute* the queued steps
        against the pre-restore weights, a wasted dispatch whose updates the
        restore immediately overwrites."""
        if model._pending is not None:
            old = model._pending[-1]
            if old._value is None:
                old._dropped = True
                old._drop_reason = reason
        model._pending = None
        model._pending_grads = None
        opt = getattr(model, "_optimizer", None)
        if opt is not None:
            for entry in opt._queue:
                entry[5]._queued_on = None
                entry[5]._dropped = True
                entry[5]._drop_reason = reason
            opt._queue = []
            opt._accum_grads = None
            opt._accum_count = 0
            opt._cycle_mstate = None

    def _full_state_like(self, model: PreparedModel, optimizer: "PreparedOptimizer"):
        """Template tree for the lossless managed state: weights + buffers +
        optimizer moments + the RNG stream position (accelerator key, backward
        base key, backward counter)."""
        # zeros template so a never-stepped (or weights-only-restored) run
        # still has the structure to save/load into; under
        # weight_update_sharding this also establishes the flat sharded layout
        optimizer._ensure_opt_state()
        tree = {
            "params": model._params,
            "model_state": model._model_state,
            "opt_state": optimizer.opt_state,
            "rng_key": self._key,
            "bwd_key": model._bwd_key,
            "bwd_counter": np.asarray(model._bwd_counter, np.int64),
        }
        if optimizer._comm_state is not None:
            # comm_hook="bf16_ef": the error-feedback residual is training
            # state — dropping it on resume would re-bias the first steps
            # after restore. Only present when the hook carries state, so
            # hook-less checkpoints keep their historical structure.
            tree["comm_state"] = optimizer._comm_state
        if optimizer._skipped is not None:
            # guard skip counters round-trip like the residual: the rollback
            # policy's consecutive-run must survive a resume
            tree["skipped_steps"] = optimizer._skipped
        return tree

    def save_state(
        self,
        model: PreparedModel,
        optimizer: "PreparedOptimizer",
        save_dir: str,
        epoch: int = 0,
        step: Optional[int] = None,
        cursor: Optional[dict] = None,
    ):
        """Lossless full-training-state save — the HF ``save_state`` analog
        (``save_model`` keeps the reference's weights-only contract,
        multi-GPU-training-accelerate.py:104-108; this adds what a restart
        needs): process 0 writes ``save_dir/state_{epoch}.npz`` holding
        params, model buffers, optimizer moments, and the RNG stream
        position, so :meth:`load_state` resumes bit-for-bit.

        ``step``/``cursor`` write a STEP-granular snapshot instead
        (``state_{epoch}_s{step}.npz`` with the v4 data cursor): the
        mid-epoch drain path — :meth:`load_state` then resumes AT that
        step with zero batches replayed."""
        model._flush_queues()  # queued fused steps are committed updates
        model._check_not_lost()
        if model._params is None:
            raise RuntimeError(
                "save_state needs an initialized model: run one forward or a "
                "training step first"
            )
        if optimizer._accum_count:
            raise RuntimeError(
                "save_state mid-gradient-accumulation-cycle would silently "
                "lose the partial cycle; call optimizer.flush_accumulation() "
                "first (the entrypoint's epoch boundary does)"
            )
        tree = self._full_state_like(model, optimizer)
        cursor_acc = None
        if cursor is not None:
            cursor = dict(cursor)
            cursor.setdefault("version", ckpt.FORMAT_VERSION)
            cursor.setdefault("epoch", int(epoch))
            if step is not None:
                cursor.setdefault("step", int(step))
            cursor_acc = cursor.pop("acc", None)
        # one writer discipline for every checkpoint flavor: cross-host
        # gather (collective) -> process-0 write -> barrier; world_size
        # stamps the v2 topology record so the state can reshard elastically
        ckpt.save_on_main(
            save_dir, epoch, tree, prefix="state",
            world_size=int(self.mesh.devices.size),
            step=step, cursor=cursor, cursor_acc=cursor_acc,
        )

    def load_state(
        self, model: PreparedModel, optimizer: "PreparedOptimizer", save_dir: str
    ) -> int:
        """Restore the newest ``state_{epoch}.npz`` written by
        :meth:`save_state` (the managed resume path). Returns the next epoch
        to train (0 when no state file exists — fresh start). The model must
        be initialized (one forward, even a lazy un-materialized one,
        suffices) so the structure to load into exists.

        A step-granular snapshot (``state_{epoch}_s{step}.npz``, written by
        a mid-epoch drain) restores too: its v4 data cursor lands in
        ``self.last_restore_cursor`` and the return value is the cursor's
        OWN epoch — the driver continues that epoch at the cursor step with
        zero batches replayed. ``last_restore_cursor`` is None after an
        epoch-granular restore."""
        self.last_restore_cursor = None
        found = ckpt.latest(save_dir, prefix="state")
        if found is None:
            # fresh start: a no-op call must not touch in-flight work
            return 0
        # discard (don't execute) anything staged against pre-restore weights
        self._discard_staged_work(model, "load_state discarded the staged step")
        if model._params is _LOST_TO_FAILED_FLUSH:
            raise RuntimeError(
                "this model's buffers were lost to a failed fused dispatch; "
                "re-prepare it (accelerator.prepare) and run one forward, "
                "then load_state"
            )
        if model._params is None:
            raise RuntimeError(
                "load_state needs an initialized model: run one forward "
                "(model(x)) first so the parameter structure exists"
            )
        like = self._full_state_like(model, optimizer)
        path, epoch = found
        # elastic resume: a state written on a different world size reshards
        # onto THIS mesh (weight-update-sharded flat moments re-pad; the
        # managed EF residual is a tree of parameter-shaped leaves, already
        # world-independent). The reshard surfaces as typed event dicts in
        # `last_restore_events` (the SAME construction the native driver
        # uses) for the entrypoint to land in history.jsonl once the
        # run_meta header exists.
        world = int(self.mesh.devices.size)
        actions: list = []
        restored, topo = ckpt.load_with_topology(
            path, like, world_size=world, reshard_actions=actions
        )
        self.last_restore_events = ckpt.build_reshard_events(
            path, epoch, topo, world, actions
        )
        cursor = ckpt.read_cursor(path)
        if cursor is not None and actions:
            # a resharded restore changed the data order the cursor's plan
            # key describes — poison it so the driver redoes the epoch
            # instead of resuming a plan that no longer exists
            cursor["plan_key"] = None
        meta = ckpt.read_meta(path)
        if cursor is not None:
            self.last_restore_cursor = cursor
            next_epoch = int(cursor.get("epoch", epoch))
        elif not meta.get("completed", 1):
            # legacy emergency save (no cursor): redo the interrupted epoch
            next_epoch = epoch
        else:
            next_epoch = epoch + 1
        model._params, model._model_state = replicate(
            self.mesh, (restored["params"], restored["model_state"])
        )
        if isinstance(optimizer.optimizer, _FlatShardedUpdate):
            # flat sharded layout: moments go back SHARDED, not replicated
            optimizer.opt_state = optimizer.optimizer.place_state(
                restored["opt_state"]
            )
        else:
            optimizer.opt_state = replicate(self.mesh, restored["opt_state"])
        if "comm_state" in restored:
            optimizer._comm_state = replicate(self.mesh, restored["comm_state"])
        if "skipped_steps" in restored:
            optimizer._skipped = replicate(self.mesh, restored["skipped_steps"])
        self._key = restored["rng_key"]
        model._bwd_key = restored["bwd_key"]
        model._bwd_counter = int(restored["bwd_counter"])
        return next_epoch

    def gather(self, x):
        """Concatenate a data-sharded array's shards onto every host."""
        from jax.experimental import multihost_utils

        if jax.process_count() > 1:
            return multihost_utils.process_allgather(x)
        return np.asarray(x)

    def print(self, *args, **kwargs):
        if self.is_local_main_process:
            print(*args, **kwargs)
