"""Module protocol: pure functional layers with explicit parameter pytrees.

A ``Module`` is a hyperparameter container with two methods:

- ``init(key, x) -> (params, state)``    — create parameters from an input
  *shape* (``x`` may be a concrete array or a ``jax.ShapeDtypeStruct``);
- ``apply(params, state, x, ctx) -> (y, new_state)`` — the forward pass.
  ``state`` carries non-trainable buffers (BatchNorm running stats); layers
  without buffers use ``()`` and return it unchanged.

``ctx`` (:class:`Context`) threads the dynamic bits: ``train`` flag, a PRNG
key for stochastic layers, and the mesh ``axis_name`` for cross-replica
statistic sync (the SyncBatchNorm contract). It is constructed inside the
jitted step function, so ``rng`` may be a tracer while ``train``/``axis_name``
stay static.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax


class Context:
    """Dynamic forward-pass context.

    ``sample_weight``: optional per-sample 0/1 mask aligned with batch axis 0
    (the static-shape padding convention, tpuddp/data/loader.py) so that
    batch-statistic layers (BatchNorm) can exclude padded rows — padding must
    not bias running statistics (torch feeds a ragged last batch instead)."""

    __slots__ = ("train", "rng", "axis_name", "sample_weight")

    def __init__(
        self,
        train: bool = False,
        rng=None,
        axis_name: Optional[str] = None,
        sample_weight=None,
    ):
        self.train = train
        self.rng = rng
        self.axis_name = axis_name
        self.sample_weight = sample_weight

    def child(self, i: int) -> "Context":
        """Context for the i-th submodule: fold the index into the key so each
        stochastic layer draws independently."""
        rng = None if self.rng is None else jax.random.fold_in(self.rng, i)
        return Context(self.train, rng, self.axis_name, self.sample_weight)


def _sds(x) -> jax.ShapeDtypeStruct:
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


class Module:
    """Base class. Subclasses implement ``init`` and ``apply``."""

    def init(self, key, x) -> Tuple[Any, Any]:
        return (), ()

    def apply(self, params, state, x, ctx: Context):
        raise NotImplementedError

    def init_with_output_shape(self, key, x):
        """init + the output ShapeDtypeStruct (no FLOPs: uses eval_shape)."""
        params, state = self.init(key, x)
        out = jax.eval_shape(
            lambda p, s, v: self.apply(p, s, v, Context(train=False))[0],
            params,
            state,
            _sds(x),
        )
        return params, state, out

    # Iteration hook so tree-walking utilities (convert_sync_batchnorm) work.
    def children(self):
        return ()

    def divergent_state(self) -> Optional[bool]:
        """Whether THIS module's own buffers can diverge across replicas under
        data parallelism (per-replica batch statistics, counters, ...) — the
        protocol behind ``sync_buffers="none"`` validation
        (tpuddp/nn/norm.py:has_divergent_buffers).

        Three-valued by design so the validation holds BY CONSTRUCTION:

        The declaration covers the module's OWN buffers only — children are
        always walked separately by the checker:

        - ``True``  — diverges (BatchNorm with unsynced running stats);
        - ``False`` — the module vouches its own state is replica-invariant
          (or that it has none beyond its children's); variable-creating
          modules must declare this explicitly (Linear, Conv2d, Sequential,
          BasicBlock do);
        - ``None``  (this default) — undeclared. Any module that creates
          variables (overrides ``init``) but never declared its divergence is
          treated as divergent: a future stateful layer cannot silently slip
          past ``sync_buffers="none"`` validation by being forgotten.
          Modules that don't override ``init`` are stateless by construction.
        """
        return None


class Sequential(Module):
    """Composes modules in order; params/state are tuples over children."""

    def __init__(self, *layers: Module):
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)):
            layers = tuple(layers[0])
        self.layers = tuple(layers)

    def init(self, key, x):
        params, states = [], []
        x = _sds(x)
        for i, layer in enumerate(self.layers):
            p, s, x = layer.init_with_output_shape(jax.random.fold_in(key, i), x)
            params.append(p)
            states.append(s)
        return tuple(params), tuple(states)

    def apply(self, params, state, x, ctx: Context):
        new_states = []
        for i, layer in enumerate(self.layers):
            x, s = layer.apply(params[i], state[i], x, ctx.child(i))
            new_states.append(s)
        return x, tuple(new_states)

    def children(self):
        return self.layers

    def divergent_state(self) -> bool:
        return False  # composes children only; owns no buffers of its own

    def __getitem__(self, i):
        return self.layers[i]

    def __len__(self):
        return len(self.layers)
