"""Losses. CrossEntropyLoss matches the reference's criterion
(multi-GPU-training-torch.py:248): softmax cross-entropy on integer labels,
default mean reduction. A ``weights`` argument supports masked (padded) final
batches so eval shapes stay static on TPU while the sample-weighted metric math
of the reference (:129-132,198-206) stays exact.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def cross_entropy(
    logits: jax.Array,
    labels: jax.Array,
    reduction: str = "mean",
    weights: Optional[jax.Array] = None,
) -> jax.Array:
    """Softmax cross-entropy. logits: (N, C) float, labels: (N,) int.

    reduction: 'mean' (weighted mean), 'sum', or 'none'.
    weights: optional per-sample weights/mask (N,).

    Higher-rank logits (e.g. a language model's ``(B, T, V)`` with ``(B, T)``
    labels/weights) flatten to per-token rows first — the token IS the sample
    in that regime, so the weighted metric math applies unchanged
    (``reduction='none'`` then returns the flattened per-token losses).
    """
    logits = logits.astype(jnp.float32)  # stable softmax even for bf16 nets
    if logits.ndim > 2:
        logits = logits.reshape(-1, logits.shape[-1])
        labels = labels.reshape(-1)
        if weights is not None:
            weights = weights.reshape(-1)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    true_logit = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    losses = logz - true_logit
    if weights is not None:
        losses = losses * weights
    if reduction == "none":
        return losses
    if reduction == "sum":
        return jnp.sum(losses)
    if reduction == "mean":
        if weights is not None:
            # an all-padding (weight-0) batch means 0 loss, not 0/0 — the
            # grad-accumulation tail pads whole micro-batches to a static
            # cycle length (training/loop.py) and their grads must vanish
            denom = jnp.sum(weights)
            denom = jnp.where(denom == 0, 1.0, denom)
        else:
            denom = losses.shape[0]
        return jnp.sum(losses) / denom
    raise ValueError(f"unknown reduction {reduction!r}")


class CrossEntropyLoss:
    """Callable criterion object, mirroring ``nn.CrossEntropyLoss()``."""

    def __init__(self, reduction: str = "mean"):
        self.reduction = reduction

    def __call__(self, logits, labels, weights=None):
        # Managed-API hook: applied to a prepared model's deferred outputs
        # (tpuddp.accelerate.LazyForward), return a deferred loss that
        # Accelerator.backward executes as one fused fwd+bwd.
        bind = getattr(logits, "_tpuddp_bind_loss", None)
        if bind is not None:
            return bind(self, labels, weights)
        return cross_entropy(logits, labels, self.reduction, weights)
