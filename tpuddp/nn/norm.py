"""BatchNorm with optional cross-replica statistic synchronization.

This owns the SyncBatchNorm contract the reference documents but does not code
(README.md:79-81; SURVEY.md §2b #16): under data parallelism, per-device batch
statistics are biased toward the local shard, so ``sync=True`` computes the
batch mean / mean-of-squares with ``lax.pmean`` over the ``"data"`` mesh axis
before normalizing — every replica then normalizes with *global*-batch
statistics, exactly what ``torch.nn.SyncBatchNorm`` does with its CUDA kernels,
here as two fused XLA collectives over ICI.

torch-parity details kept: momentum 0.1 (new-stat weight), eps 1e-5, biased
variance for normalization but **unbiased** variance for the running buffer.

Two honesty details beyond torch:

- **Padded rows are excluded from batch statistics.** tpuddp pads the final
  partial batch to a static shape with weight-0 rows (TPU-first: no ragged
  recompiles); when the forward ``Context`` carries ``sample_weight``, the
  batch mean/var are weighted sums so padding cannot bias the running stats
  (torch never sees padded rows because it feeds a ragged last batch).
- ``stable_var=True`` computes the variance two-pass (``E[(x-mean)^2]``)
  instead of the single-pass ``E[x^2]-E[x]^2``, which is cancellation-prone
  for large-mean activations; sync mode then costs a second ``pmean``.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from tpuddp.nn.core import Context, Module, Sequential
from tpuddp.utils.compat import axis_size


class BatchNorm(Module):
    """Batch normalization over all axes except the last (features).

    ``sync``: if True, batch statistics are averaged across the data-parallel
    axis (``ctx.axis_name``) — the SyncBatchNorm behavior. If False (default,
    matching plain ``nn.BatchNorm2d``), statistics are local to the replica.
    """

    def __init__(
        self,
        momentum: float = 0.1,
        eps: float = 1e-5,
        affine: bool = True,
        track_running_stats: bool = True,
        sync: bool = False,
        stable_var: bool = False,
        dtype=jnp.float32,
    ):
        self.momentum = momentum
        self.eps = eps
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.sync = sync
        self.stable_var = stable_var
        self.dtype = dtype

    def init(self, key, x):
        features = x.shape[-1]
        params = (
            {
                "scale": jnp.ones((features,), self.dtype),
                "bias": jnp.zeros((features,), self.dtype),
            }
            if self.affine
            else {}
        )
        state = (
            {
                "mean": jnp.zeros((features,), self.dtype),
                "var": jnp.ones((features,), self.dtype),
            }
            if self.track_running_stats
            else {}
        )
        return params, state

    def apply(self, params, state, x, ctx: Context):
        reduce_axes = tuple(range(x.ndim - 1))
        use_batch_stats = ctx.train or not self.track_running_stats

        if use_batch_stats:
            xs = x.astype(self.dtype)  # stats accumulate in f32 even for bf16
            ax = ctx.axis_name if self.sync else None
            w = ctx.sample_weight
            if w is not None:
                # padded (weight-0) rows are excluded from the statistics
                wb = jnp.reshape(
                    w.astype(self.dtype), (-1,) + (1,) * (x.ndim - 1)
                )
                spatial = x.size // (x.shape[0] * x.shape[-1])
                count = jnp.sum(wb) * spatial
                sum_x = jnp.sum(xs * wb, axis=reduce_axes)
            else:
                wb = None
                count = jnp.asarray(float(x.size // x.shape[-1]), self.dtype)
                sum_x = jnp.sum(xs, axis=reduce_axes)

            if self.stable_var:
                # two-pass: mean first, then E[(x-mean)^2] — no cancellation
                if ax is not None:
                    sum_x, count = lax.pmean((sum_x, count), ax)
                denom = jnp.maximum(count, 1.0)
                mean = sum_x / denom
                dev = jnp.square(xs - mean)
                sum_dev = jnp.sum(
                    dev * wb if wb is not None else dev, axis=reduce_axes
                )
                if ax is not None:
                    sum_dev = lax.pmean(sum_dev, ax)
                var = sum_dev / denom  # biased, used for normalization
            else:
                xsq = jnp.square(xs)
                sum_x2 = jnp.sum(
                    xsq * wb if wb is not None else xsq, axis=reduce_axes
                )
                if ax is not None:
                    sum_x, sum_x2, count = lax.pmean((sum_x, sum_x2, count), ax)
                denom = jnp.maximum(count, 1.0)
                mean = sum_x / denom
                var = sum_x2 / denom - jnp.square(mean)  # biased

            new_state = state
            if self.track_running_stats and ctx.train:
                m = self.momentum
                # total element count behind the stats (all replicas when sync)
                n = denom * (axis_size(ax) if ax is not None else 1)
                unbiased = var * (n / jnp.maximum(n - 1.0, 1.0))
                # a fully-padded (count==0) shard must leave the running
                # buffers untouched, not decay them toward mean=0/var=0
                has_data = count > 0
                new_state = {
                    "mean": jnp.where(
                        has_data, (1 - m) * state["mean"] + m * mean, state["mean"]
                    ),
                    "var": jnp.where(
                        has_data, (1 - m) * state["var"] + m * unbiased, state["var"]
                    ),
                }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
            xs = x.astype(self.dtype)

        inv = lax.rsqrt(var + self.eps)
        y = (xs - mean) * inv
        if self.affine:
            y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), new_state

    def divergent_state(self) -> bool:
        # running statistics accumulate the LOCAL shard's batches unless
        # cross-replica synced — the canonical divergent buffer
        return self.track_running_stats and not self.sync


class LayerNorm(Module):
    """Layer normalization over the last (feature) axis — the transformer
    family's norm (tpuddp/models/transformer.py). Per-sample statistics, so
    unlike :class:`BatchNorm` there are no running buffers, nothing diverges
    across replicas, and train/eval are the same math.

    torch parity: ``nn.LayerNorm(features)`` defaults — eps 1e-5, elementwise
    affine, biased variance. Statistics accumulate in f32 even for bf16
    activations (the BatchNorm convention above)."""

    def __init__(self, eps: float = 1e-5, affine: bool = True, dtype=jnp.float32):
        self.eps = eps
        self.affine = affine
        self.dtype = dtype

    def init(self, key, x):
        features = x.shape[-1]
        params = (
            {
                "scale": jnp.ones((features,), self.dtype),
                "bias": jnp.zeros((features,), self.dtype),
            }
            if self.affine
            else {}
        )
        return params, ()

    def apply(self, params, state, x, ctx: Context):
        xs = x.astype(self.dtype)
        mean = jnp.mean(xs, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(xs - mean), axis=-1, keepdims=True)  # biased
        y = (xs - mean) * lax.rsqrt(var + self.eps)
        if self.affine:
            y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), state

    def divergent_state(self) -> bool:
        return False  # parameters only, no buffers


def has_divergent_buffers(module: Module) -> bool:
    """True when the module tree contains a buffer that *diverges across
    replicas* under data parallelism. Used by the DDP step builder to refuse
    ``sync_buffers="none"`` configs that would publish per-replica-divergent
    buffers as replicated.

    The judgment is the :meth:`Module.divergent_state` protocol, so it holds
    by construction: ``divergent_state`` speaks for a module's OWN buffers
    (children are always walked separately), and ANY module that creates
    variables (overrides ``init``) — leaf or container — without declaring
    its divergence is conservatively treated as divergent. A future stateful
    layer cannot silently bypass the validation by not being special-cased
    here; built-in variable-creating modules (Linear, Conv2d, Sequential,
    BasicBlock, BatchNorm) all declare."""
    own = module.divergent_state()
    if own:
        return True
    if own is None and type(module).init is not Module.init:
        # undeclared variable-creating module: could hold divergent state
        return True
    return any(has_divergent_buffers(c) for c in module.children())


def convert_sync_batchnorm(module: Module) -> Module:
    """Flip every BatchNorm in a module tree to ``sync=True`` — API parity with
    ``torch.nn.SyncBatchNorm.convert_sync_batchnorm`` (reference README.md:79-81).
    Mutates hyperparameters in place (parameters/state are unaffected) and
    returns the module for chaining."""
    if isinstance(module, BatchNorm):
        module.sync = True
    for child in module.children():
        convert_sync_batchnorm(child)
    return module
