"""BatchNorm with optional cross-replica statistic synchronization.

This owns the SyncBatchNorm contract the reference documents but does not code
(README.md:79-81; SURVEY.md §2b #16): under data parallelism, per-device batch
statistics are biased toward the local shard, so ``sync=True`` computes the
batch mean / mean-of-squares with ``lax.pmean`` over the ``"data"`` mesh axis
before normalizing — every replica then normalizes with *global*-batch
statistics, exactly what ``torch.nn.SyncBatchNorm`` does with its CUDA kernels,
here as two fused XLA collectives over ICI.

torch-parity details kept: momentum 0.1 (new-stat weight), eps 1e-5, biased
variance for normalization but **unbiased** variance for the running buffer.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
from jax import lax

from tpuddp.nn.core import Context, Module, Sequential


class BatchNorm(Module):
    """Batch normalization over all axes except the last (features).

    ``sync``: if True, batch statistics are averaged across the data-parallel
    axis (``ctx.axis_name``) — the SyncBatchNorm behavior. If False (default,
    matching plain ``nn.BatchNorm2d``), statistics are local to the replica.
    """

    def __init__(
        self,
        momentum: float = 0.1,
        eps: float = 1e-5,
        affine: bool = True,
        track_running_stats: bool = True,
        sync: bool = False,
        dtype=jnp.float32,
    ):
        self.momentum = momentum
        self.eps = eps
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.sync = sync
        self.dtype = dtype

    def init(self, key, x):
        features = x.shape[-1]
        params = (
            {
                "scale": jnp.ones((features,), self.dtype),
                "bias": jnp.zeros((features,), self.dtype),
            }
            if self.affine
            else {}
        )
        state = (
            {
                "mean": jnp.zeros((features,), self.dtype),
                "var": jnp.ones((features,), self.dtype),
            }
            if self.track_running_stats
            else {}
        )
        return params, state

    def apply(self, params, state, x, ctx: Context):
        reduce_axes = tuple(range(x.ndim - 1))
        use_batch_stats = ctx.train or not self.track_running_stats

        if use_batch_stats:
            mean = jnp.mean(x, axis=reduce_axes)
            mean_sq = jnp.mean(jnp.square(x), axis=reduce_axes)
            n = x.size // x.shape[-1]
            if self.sync and ctx.axis_name is not None:
                mean = lax.pmean(mean, ctx.axis_name)
                mean_sq = lax.pmean(mean_sq, ctx.axis_name)
                n = n * lax.axis_size(ctx.axis_name)
            var = mean_sq - jnp.square(mean)  # biased, used for normalization
            new_state = state
            if self.track_running_stats and ctx.train:
                m = self.momentum
                unbiased = var * (n / max(n - 1, 1))
                new_state = {
                    "mean": (1 - m) * state["mean"] + m * mean,
                    "var": (1 - m) * state["var"] + m * unbiased,
                }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state

        inv = lax.rsqrt(var + self.eps)
        y = (x - mean) * inv
        if self.affine:
            y = y * params["scale"] + params["bias"]
        return y.astype(x.dtype), new_state


def convert_sync_batchnorm(module: Module) -> Module:
    """Flip every BatchNorm in a module tree to ``sync=True`` — API parity with
    ``torch.nn.SyncBatchNorm.convert_sync_batchnorm`` (reference README.md:79-81).
    Mutates hyperparameters in place (parameters/state are unaffected) and
    returns the module for chaining."""
    if isinstance(module, BatchNorm):
        module.sync = True
    for child in module.children():
        convert_sync_batchnorm(child)
    return module
