"""tpuddp.nn — a compact functional neural-net layer library.

Pure init/apply modules over explicit parameter pytrees (no framework
dependency): the compute path is jax.numpy + lax so everything fuses under jit
and tiles onto the TPU MXU. Layout is NHWC (TPU-native), vs the reference
stack's NCHW.
"""

from tpuddp.nn.core import Context, Module, Sequential  # noqa: F401
from tpuddp.nn.layers import (  # noqa: F401
    AdaptiveAvgPool2d,
    AvgPool2d,
    Conv2d,
    SpaceToDepthConv2d,
    Dropout,
    Embedding,
    Flatten,
    Linear,
    MaxPool2d,
    ReLU,
)
from tpuddp.nn.norm import (  # noqa: F401
    BatchNorm,
    LayerNorm,
    convert_sync_batchnorm,
)
from tpuddp.nn.loss import CrossEntropyLoss, cross_entropy  # noqa: F401

__all__ = [
    "Context",
    "Module",
    "Sequential",
    "Linear",
    "Conv2d",
    "SpaceToDepthConv2d",
    "MaxPool2d",
    "AvgPool2d",
    "AdaptiveAvgPool2d",
    "ReLU",
    "Dropout",
    "Embedding",
    "Flatten",
    "BatchNorm",
    "LayerNorm",
    "convert_sync_batchnorm",
    "CrossEntropyLoss",
    "cross_entropy",
]
