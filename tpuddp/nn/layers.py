"""Standard layers. NHWC layout; weights HWIO (the lax.conv native layout on
TPU, so XLA tiles convs straight onto the MXU without transposes).

Initialization follows the same fan-in uniform scheme the reference's model
zoo inherits from torch (U(-1/sqrt(fan_in), 1/sqrt(fan_in)) for Linear/Conv),
so loss curves are comparable at matched seeds-in-distribution.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from tpuddp.nn.core import Context, Module

IntOr2 = Union[int, Tuple[int, int]]


def _pair(v: IntOr2) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


class Linear(Module):
    """y = x @ W + b, W: (in, out). ``in_features`` is inferred at init."""

    def __init__(self, out_features: int, use_bias: bool = True, dtype=jnp.float32):
        self.out_features = out_features
        self.use_bias = use_bias
        self.dtype = dtype

    def init(self, key, x):
        in_features = x.shape[-1]
        bound = 1.0 / math.sqrt(in_features)
        wk, bk = jax.random.split(key)
        params = {
            "weight": jax.random.uniform(
                wk, (in_features, self.out_features), self.dtype, -bound, bound
            )
        }
        if self.use_bias:
            params["bias"] = jax.random.uniform(
                bk, (self.out_features,), self.dtype, -bound, bound
            )
        return params, ()

    def apply(self, params, state, x, ctx: Context):
        # params stay f32 masters; compute follows the activation dtype so a
        # bf16 pipeline runs the matmul on the MXU in bf16 (mixed precision)
        y = x @ params["weight"].astype(x.dtype)
        if self.use_bias:
            y = y + params["bias"].astype(x.dtype)
        return y, state

    def divergent_state(self) -> bool:
        return False  # parameters only, no buffers


class Embedding(Module):
    """Token-id lookup table: ``x`` int32 ids of any shape -> ``(*x.shape,
    features)`` rows of ``weight``. torch ``nn.Embedding`` parity: N(0, 1)
    init. The transformer LM head ties to this table (logits = h @ W.T), so
    the weight layout is ``(num_embeddings, features)`` exactly like torch."""

    def __init__(self, num_embeddings: int, features: int, dtype=jnp.float32):
        self.num_embeddings = num_embeddings
        self.features = features
        self.dtype = dtype

    def init(self, key, x):
        params = {
            "weight": jax.random.normal(
                key, (self.num_embeddings, self.features), self.dtype
            )
        }
        return params, ()

    def apply(self, params, state, x, ctx: Context):
        ids = jnp.asarray(x).astype(jnp.int32)
        return jnp.take(params["weight"], ids, axis=0), state

    def divergent_state(self) -> bool:
        return False  # parameters only, no buffers


class Conv2d(Module):
    """2-D convolution, NHWC / HWIO. ``padding`` is 'SAME', 'VALID', or an int
    (symmetric, torch-style)."""

    def __init__(
        self,
        features: int,
        kernel_size: IntOr2,
        strides: IntOr2 = 1,
        padding: Union[str, int, Sequence[Tuple[int, int]]] = 0,
        use_bias: bool = True,
        dtype=jnp.float32,
    ):
        self.features = features
        self.kernel_size = _pair(kernel_size)
        self.strides = _pair(strides)
        self.padding = padding
        self.use_bias = use_bias
        self.dtype = dtype

    def _pad_arg(self):
        if isinstance(self.padding, str):
            return self.padding
        if isinstance(self.padding, int):
            p = self.padding
            return [(p, p), (p, p)]
        return list(self.padding)

    def init(self, key, x):
        in_ch = x.shape[-1]
        kh, kw = self.kernel_size
        fan_in = in_ch * kh * kw
        bound = 1.0 / math.sqrt(fan_in)
        wk, bk = jax.random.split(key)
        params = {
            "weight": jax.random.uniform(
                wk, (kh, kw, in_ch, self.features), self.dtype, -bound, bound
            )
        }
        if self.use_bias:
            params["bias"] = jax.random.uniform(
                bk, (self.features,), self.dtype, -bound, bound
            )
        return params, ()

    def apply(self, params, state, x, ctx: Context):
        y = lax.conv_general_dilated(
            x,
            params["weight"].astype(x.dtype),
            window_strides=self.strides,
            padding=self._pad_arg(),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state

    def divergent_state(self) -> bool:
        return False  # parameters only, no buffers


class SpaceToDepthConv2d(Conv2d):
    """Exact reparameterization of a strided conv as space-to-depth + a
    unit-stride conv — the classic TPU recipe for thin-channel strided stems
    (MLPerf ResNet's conv1 trick, here for AlexNet's 11x11/s4 3-channel
    stem): the original form contracts only ``C*kw`` values per MXU pass and
    its backward needs strided grad-convolutions; the blocked form contracts
    ``s*s*C`` channels per tap at stride 1.

    Mathematically identical to :class:`Conv2d` (same sum, re-associated):
    the input is blocked ``(H, W, C) -> (H/s, W/s, s*s*C)`` and the kernel is
    zero-padded to an ``s`` multiple and reshaped to match. Parameters keep
    the ORIGINAL ``(kh, kw, C, F)`` layout — torch imports, checkpoints, and
    init are interchangeable with ``Conv2d``; the blocked weight view is a
    tiny reshape XLA fuses into the conv. Requires square integer stride
    (= the block size) and integer symmetric padding."""

    def __init__(self, features, kernel_size, strides, padding=0, use_bias=True, dtype=jnp.float32):
        super().__init__(features, kernel_size, strides, padding, use_bias, dtype)
        if self.strides[0] != self.strides[1] or self.strides[0] < 2:
            raise ValueError(
                f"SpaceToDepthConv2d needs a square stride >= 2 (the block "
                f"size); got {self.strides}"
            )
        if not isinstance(padding, int):
            raise ValueError(
                "SpaceToDepthConv2d supports integer (symmetric) padding only"
            )

    def apply(self, params, state, x, ctx: Context):
        s = self.strides[0]
        kh, kw = self.kernel_size
        p = self.padding
        n, h, w, c = x.shape
        oh = (h + 2 * p - kh) // s + 1
        ow = (w + 2 * p - kw) // s + 1
        kbh, kbw = -(-kh // s), -(-kw // s)  # ceil
        # pre-pad so every window start (s*i - p) + p is block-aligned, with
        # enough right/bottom slack for the last window and an s multiple
        def pads(dim, o, k):
            right = max(p, s * (o - 1) + k - dim - p)
            total = dim + p + right
            right += (-total) % s
            return (p, right)

        ph, pw = pads(h, oh, kbh * s), pads(w, ow, kbw * s)
        xp = jnp.pad(x, ((0, 0), ph, pw, (0, 0)))
        bh, bw = xp.shape[1] // s, xp.shape[2] // s
        xb = (
            xp.reshape(n, bh, s, bw, s, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, bh, bw, s * s * c)
        )
        wk = params["weight"].astype(x.dtype)
        wk = jnp.pad(wk, ((0, kbh * s - kh), (0, kbw * s - kw), (0, 0), (0, 0)))
        wb = (
            wk.reshape(kbh, s, kbw, s, c, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(kbh, kbw, s * s * c, self.features)
        )
        y = lax.conv_general_dilated(
            xb, wb, window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y[:, :oh, :ow, :]
        if self.use_bias:
            y = y + params["bias"].astype(y.dtype)
        return y, state


class _Pool2d(Module):
    def __init__(self, window: IntOr2, strides: Optional[IntOr2] = None, padding: Union[str, int] = 0):
        self.window = _pair(window)
        self.strides = _pair(strides) if strides is not None else self.window
        self.padding = padding

    def _pad_arg(self):
        if isinstance(self.padding, str):
            return self.padding
        p = self.padding
        return [(0, 0), (p, p), (p, p), (0, 0)]


class MaxPool2d(_Pool2d):
    def apply(self, params, state, x, ctx: Context):
        init_val = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        y = lax.reduce_window(
            x,
            init_val,
            lax.max,
            (1, *self.window, 1),
            (1, *self.strides, 1),
            self._pad_arg(),
        )
        return y, state


class AvgPool2d(_Pool2d):
    def apply(self, params, state, x, ctx: Context):
        wh, ww = self.window
        y = lax.reduce_window(
            x, 0.0, lax.add, (1, wh, ww, 1), (1, *self.strides, 1), self._pad_arg()
        )
        return y / (wh * ww), state


class AdaptiveAvgPool2d(Module):
    """torch-style adaptive average pooling to a fixed (H_out, W_out).

    Bin i covers [floor(i*N/M), ceil((i+1)*N/M)). When the bins are UNIFORM
    (same size and stride — e.g. AlexNet's 13->6, or any divisible shape)
    the layer lowers to a plain ``reduce_window`` average, whose VJP is far
    cheaper than the general path's (see :meth:`_uniform`; measured -0.08
    ms/step on AlexNet b128). Ragged bins fall back to a 2-D integral image
    (cumsum) with *static* gather indices: four corner lookups + area
    divide. Both paths are fully shape-static; no dynamic control flow.
    """

    def __init__(self, output_size: IntOr2):
        self.output_size = _pair(output_size)

    @staticmethod
    def _bounds_list(n_in: int, n_out: int):
        starts = [(i * n_in) // n_out for i in range(n_out)]
        ends = [-(-((i + 1) * n_in) // n_out) for i in range(n_out)]  # ceil div
        return starts, ends

    @classmethod
    def _bounds(cls, n_in: int, n_out: int):
        starts, ends = cls._bounds_list(n_in, n_out)
        return jnp.array(starts), jnp.array(ends)

    @classmethod
    def _uniform(cls, n_in: int, n_out: int):
        """If every bin has the same size and stride, return (window, stride)
        — the bins then ARE a plain average pool (e.g. AlexNet's 13->6: bins
        [0,3) [2,5) ... = window 3 stride 2), whose reduce_window lowering
        and VJP are far cheaper than the integral-image gather (no f32 cumsum
        chain in the backward). None when the bins are ragged or upsampling
        (n_out > n_in repeats bins: stride 0 is not a pool)."""
        starts, ends = cls._bounds_list(n_in, n_out)
        sizes = {e - s for s, e in zip(starts, ends)}
        strides = {b - a for a, b in zip(starts, starts[1:])} or {1}
        if len(sizes) == 1 and len(strides) == 1 and 0 not in strides:
            return sizes.pop(), strides.pop()
        return None

    def apply(self, params, state, x, ctx: Context):
        n, h, w, c = x.shape
        oh, ow = self.output_size
        uh, uw = self._uniform(h, oh), self._uniform(w, ow)
        if uh is not None and uw is not None:
            (kh, sh), (kw, sw) = uh, uw
            y = lax.reduce_window(
                x.astype(jnp.float32), 0.0, lax.add,
                (1, kh, kw, 1), (1, sh, sw, 1), "VALID",
            )
            return (y / (kh * kw)).astype(x.dtype), state
        in_dtype = x.dtype
        x = x.astype(jnp.float32)  # integral-image sums need f32 accumulation
        # integral image with a leading zero row/col: I[i, j] = sum(x[:i, :j])
        ii = jnp.cumsum(jnp.cumsum(x, axis=1), axis=2)
        ii = jnp.pad(ii, ((0, 0), (1, 0), (1, 0), (0, 0)))
        hs, he = self._bounds(h, oh)
        ws, we = self._bounds(w, ow)
        # window sum via 4 corners, broadcast over output grid
        a = ii[:, he[:, None], we[None, :], :]
        b = ii[:, he[:, None], ws[None, :], :]
        c_ = ii[:, hs[:, None], we[None, :], :]
        d = ii[:, hs[:, None], ws[None, :], :]
        sums = a - b - c_ + d
        areas = ((he - hs)[:, None] * (we - ws)[None, :]).astype(jnp.float32)
        return (sums / areas[None, :, :, None]).astype(in_dtype), state


class ReLU(Module):
    def apply(self, params, state, x, ctx: Context):
        return jax.nn.relu(x), state


class Flatten(Module):
    def apply(self, params, state, x, ctx: Context):
        return x.reshape(x.shape[0], -1), state


class Dropout(Module):
    """Inverted dropout; active only when ``ctx.train`` and ``ctx.rng`` given.
    Per-replica masks come from the step fn folding ``lax.axis_index`` into the
    key (tpuddp.seeding.fold_in_axis_index)."""

    def __init__(self, p: float = 0.5):
        if not 0.0 <= p < 1.0:
            raise ValueError(f"dropout p must be in [0, 1), got {p}")
        self.p = p

    def apply(self, params, state, x, ctx: Context):
        if not ctx.train or self.p == 0.0:
            return x, state
        if ctx.rng is None:
            raise ValueError("Dropout in train mode requires ctx.rng")
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(ctx.rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0).astype(x.dtype), state
