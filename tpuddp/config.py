"""YAML config system — schema parity with the reference's settings file
(local_settings.yaml:1-13; parsed identically in all three __main__ blocks,
multi-GPU-training-torch.py:282-308).

Kept: ``script_path``, ``out_dir``, ``optional_args.{set_epoch,print_rand}``,
and the provenance copy of the settings file into ``out_dir`` (:300-303).
Retargeted: ``local.device: tpu`` with a ``local.tpu`` block (accelerator
type + num_chips) replacing the role of ``local.condor.num_gpus`` as the
world-size source; the ``local.condor`` block remains supported for the
condor submission path. New optional ``training`` block exposes the
constants the reference hardcodes (batch sizes 128/100, Adam lr 1e-3,
epochs 20, checkpoint every 5 — BASELINE.md workload constants).
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, Optional

import yaml

# Reference-hardcoded workload constants (BASELINE.md).
TRAINING_DEFAULTS = {
    "model": "alexnet",
    "dataset": "cifar10",
    "data_root": "./data",
    "train_batch_size": 128,  # per replica, multi-GPU-training-torch.py:88
    "test_batch_size": 100,  # per replica, :95
    "learning_rate": 0.001,  # :249
    "num_epochs": 20,  # :166
    "checkpoint_epoch": 5,  # :167
    "image_size": 224,  # data_and_toy_model.py:14
    "flip": None,  # RandomHorizontalFlip (:15); None -> on except for digits
    "compute_dtype": "float32",  # activation dtype: bfloat16 = mixed precision
    # (f32 master params; bf16 activations through the MXU — BASELINE.md)
    "seed": None,  # None -> fresh per run, like torch initial_seed
    "mode": "shard_map",
    "sync_bn": False,
    "scan_steps": "auto",  # K train steps fused per dispatch (lax.scan);
    # "auto" = size-resolved: up to 64, capped by a ~256MB staged-chunk
    # budget (32 when the batch size in bytes is unknowable)
    "clip_grad_norm": None,  # clip the cross-replica-AVERAGED grad (README's
    # clip-before-aggregate caveat: clipping per-shard grads then averaging
    # would differ; tpuddp clips after the pmean, identically on all replicas)
    "remat": False,  # jax.checkpoint: recompute activations in backward
    "weight_update_sharding": False,  # ZeRO-1 on ICI (arxiv 2004.13336):
    # reduce-scatter grads, 1/N-shard optimizer update per chip (moments
    # sharded over the data axis), all-gather params. shard_map mode only.
    "comm_hook": "none",  # gradient-comm hook (torch DDP comm-hook analog,
    # parallel/comm.py): "bf16" = bucketed bf16-compressed allreduce (half
    # the gradient interconnect bytes on the explicit path); "bf16_ef" adds
    # the persistent error-feedback residual (checkpointed) so compression
    # error doesn't bias convergence
    "bucket_cap_mb": 25,  # comm-hook bucket size cap (torch's bucket_cap_mb):
    # small tensors coalesce into one collective per <= cap-sized bucket
    "comm_topology": "flat",  # gradient-reduction topology (parallel/comm.py):
    # "hierarchical" = intra-host f32 reduce-scatter over the factored mesh's
    # "local" axis, COMPRESSED inter-host exchange over "host", all-gather —
    # only the compressed shard crosses the slow link. Explicit path
    # (mode: shard_map) only; excludes weight_update_sharding.
    "comm_overlap": "auto",  # segmented-backward execution (training/step.py):
    # true/auto stage the backward pass as per-segment VJP closures whose
    # segment boundaries align with bucket_cap_mb buckets, issuing each
    # segment's gradient collective the moment its buckets materialize while
    # the next segment's backward compute proceeds — torch DDP's ready-bucket
    # overlap, natively in JAX. Bitwise-identical loss trajectory to the
    # barrier step. "auto" (default) enables it only where it genuinely
    # segments (flat topology, mode: shard_map, Sequential model, no WUS/
    # remat/TP, and >= 2 bucket-aligned segments) and quietly keeps the
    # barrier step elsewhere; true refuses ineligible combos loudly; false
    # pins the barrier step.
    "topk_density": 0.1,  # comm_hook: topk_ef's keep fraction per bucket
    # (int8 values + int32 indices + per-bucket scale on the wire; 0.1 =>
    # ~87.5% fewer gradient bytes, with the unsent complement riding the
    # error-feedback residual)
    "optimizer": "adam",  # adam | sgd | sgdw | lars | lamb (tpuddp/optim.py).
    # lars/lamb apply per-LAYER trust ratios (You et al. 1708.03888 /
    # 1904.00962, the MLPerf large-batch recipe) so the bandwidth a
    # compressed hook frees converts into bigger global batches that still
    # converge; sgdw is the trust-ratio-free decoupled-decay baseline.
    "weight_decay": 0.0,  # decoupled weight decay for sgdw/lars/lamb (adam/
    # sgd keep their torch-parity L2-into-grad convention via this knob too)
    "momentum": 0.9,  # momentum for sgd/sgdw/lars
    "trust_coefficient": 0.001,  # LARS eta (the layer-wise LR scale)
    "prefetch": True,  # background-thread host batch prefetch
    "pipeline": None,  # async pipeline block (training/pipeline.py): None/
    # true -> overlapped defaults {depth: 2, host_workers: 2, device_augment:
    # true, sync_readback: false}; false -> the synchronous A/B reference
    # (no lookahead, blocking readback per dispatch); a dict overrides the
    # defaults with unknown-key refusal. Bitwise-identical at every depth.
    "deferred_metrics": False,  # managed path: epoch-end (not per-batch) metric sync
    "fuse_steps": "auto",  # managed path: K step()s per dispatch (auto, with
    # deferred_metrics: 32, capped by a ~256MB queued-batch staging budget)
    "gradient_accumulation_steps": 1,  # one averaged update every N micro-batches (both paths)
    "optimizer_state_dtype": None,  # Adam m/v storage dtype ("bfloat16" halves
    # optimizer HBM traffic; math stays f32). None -> params' dtype.
    "pretrained_path": None,  # torch checkpoint to fine-tune from (alexnet,
    # vgg11/13/16, resnet18/34 — incl. the _s2d stem variants, same checkpoints)
    "num_classes": None,  # None -> derived from training.dataset
    "resume": False,  # restore the newest checkpoint from out_dir (native:
    # ckpt_{epoch}.npz full TrainState; managed: state_{epoch}.npz)
    "auto_resume": False,  # resilience resume: restore the newest INTACT
    # checkpoint at loop entry (corrupt ones skipped; a preemption-drain
    # emergency save redoes its interrupted epoch). Env: TPUDDP_AUTO_RESUME=1
    # lets a scheduler requeue the exact same command after exit 75.
    "reshard_on_mismatch": False,  # elastic mesh failover: a checkpoint
    # written on a different (data, model) mesh shape is re-shaped in-memory
    # by the cross-topology reshaper (training/reshard.py) at restore time
    # instead of refusing with TopologyMismatch. Opt-in because a reshard
    # can reset the error-feedback residual (model-width changes) — the
    # reshard lands typed topology_change/comm_state_reset event rows.
    "keep_last": None,  # checkpoint retention: prune all but the K newest
    # ckpt_{epoch}.npz (+ .sha256 manifests) after each save; None keeps all
    "snapshot": None,  # async step-granular checkpointing (training/
    # snapshot.py): None/false -> off (epoch-granular checkpoints only);
    # true -> defaults {every_steps: 50, async: true, inflight: 2,
    # peer_redundancy: false}; a dict overrides the defaults with unknown-key
    # refusal. Armed, the loop snapshots TrainState every N optimizer steps
    # between dispatches (on-device copy + background writer — no step
    # stall), records a v4 data cursor (epoch, step, plan key, partial
    # accumulator) so auto_resume continues the interrupted epoch AT the
    # snapshot step with zero batches replayed, and with peer_redundancy
    # spills each process's shard bytes to its ring neighbor's directory so
    # one lost host directory still restores.
    "guard": None,  # numerical guard block (resilience/guard.py): true, or
    # {max_consecutive_skips, audit_every_n_epochs, on_desync, max_rollbacks}.
    # Arms the in-step non-finite-gradient firewall (a poisoned update is a
    # bitwise no-op counted in TrainState.skipped_steps), the cross-replica
    # desync auditor (wrap-time + every N epochs; divergence -> exit 77 or
    # rollback), and the epoch driver's rollback-to-last-good. None/false:
    # strict no-op — the step lowers to the identical HLO.
    "synthetic_n": None,  # (train, test) sizes for the synthetic dataset /
    # fallback; None -> (2048, 512)
    "step_stats_every": 0,  # telemetry window (tpuddp/observability): N > 0
    # writes one `step_stats` record (step-time p50/p95/p99/max, samples/sec,
    # MFU) to history.jsonl every N train steps — ONE host-side device fence
    # per window, nothing in the compiled step. 0 (default) disables window
    # rows; epoch rows always carry the full-epoch percentiles either way.
}

# Serving-engine knobs (tpuddp/serving/) — the ``serving`` block of a
# settings file, consumed by ``python -m tpuddp.serving`` and tools/loadgen.py.
# Same unknown-key-refusal contract as the ``training`` block.
SERVING_DEFAULTS = {
    "model": "toy_mlp",  # model-zoo name (tpuddp/models)
    "num_classes": 10,
    "input_shape": [32, 32, 3],  # one sample's x shape (no batch axis) — the
    # shape requests carry and the checkpoint template is initialized from
    "checkpoint_dir": None,  # restore the newest INTACT checkpoint from here
    # via training/checkpoint.restore_latest (sha256-verified, corrupt files
    # skipped); None -> fresh seeded init (CI / loadgen worlds)
    "checkpoint_prefix": "auto",  # which checkpoint family to restore:
    # "ckpt" (native TrainState files), "state" (managed full-state files),
    # or "auto" -> whichever family has the newest intact file
    "num_replicas": "auto",  # independent model replicas, one per local
    # device; "auto" -> every local device
    "max_batch_size": 32,  # coalescing ceiling: requests stack into
    # power-of-two row buckets up to this (compile cache holds at most
    # log2(max)+1 programs per sample shape)
    "max_queue_depth": 256,  # admission control: total queued requests
    # beyond this are rejected with reason "queue_full"
    "per_tenant_quota": None,  # max queued requests per tenant (None -> no
    # per-tenant bound); excess rejected with reason "tenant_quota"
    "batch_timeout_ms": 2.0,  # how long a dispatch loop waits for more rows
    # after the first request is in hand (latency/occupancy tradeoff)
    "stats_window": 64,  # completed requests per serving_stats history row
    "unhealthy_after": 3,  # graceful degradation: K consecutive dispatch
    # errors mark a replica unhealthy (stop routing to it, emit a
    # replica_unhealthy event row) and send it to PROBATION (see the
    # survivability knobs below). 0 never marks (every batch on a broken
    # replica fails individually).
    # -- survivability knobs (tpuddp/serving/survive.py, README "Serving
    # survivability"):
    "request_ttl_s": None,  # admission-time deadline: a request still
    # QUEUED this long after submit is shed with reason deadline_exceeded
    # before it wastes device time (in-flight work is never deadline-
    # killed); None -> no TTL. Clients may pass a tighter per-call
    # deadline_s to submit() either way.
    "max_recoveries": 2,  # lifetime probation episodes per replica: an
    # unhealthy replica rebuilds + canaries with jittered backoff and
    # rejoins routing on success (replica_recovered event); past this many
    # rejoins the next incident removes it permanently (the fallback, not
    # the policy)
    "recovery_attempts": 2,  # rebuild+canary tries within one probation
    # episode (resilience/retry.py jittered exponential backoff between)
    "recovery_backoff_s": 0.1,  # base backoff between in-episode tries
    "retry_budget": 0,  # per-tenant transient-dispatch retry tokens: a
    # failed batch's requests re-enter the queue (front of lane) within
    # this budget instead of failing through; tokens are refunded when a
    # retried request succeeds. 0 disables (failures surface immediately).
    "seed": 0,  # fresh-init parameter seed (ignored with a checkpoint)
    "decode": None,  # autoregressive decode block (tpuddp/serving/decode/):
    # None -> request-granularity CNN serving only; a dict (or true for all
    # defaults) arms the token-level engine — see DECODE_DEFAULTS. Same
    # unknown-key-refusal contract as every other block.
}


# Autoregressive decode knobs (tpuddp/serving/decode/) — the
# ``serving.decode`` block, consumed by ``python -m tpuddp.serving --decode``
# and ``tools/loadgen.py --decode``. Same unknown-key-refusal contract.
DECODE_DEFAULTS = {
    "model": "transformer_tiny",  # model-zoo name; must be a TransformerLM
    # family member (prefill/decode_step protocol, tpuddp/models/transformer.py)
    "vocab_size": 256,  # token id space (the model's num_classes)
    "checkpoint_dir": None,  # restore params via the integrity path (the
    # request-granularity engine's contract); None -> fresh seeded init
    "checkpoint_prefix": "auto",
    "num_replicas": 1,  # independent decode replicas, each with its own KV
    # pool + slot set + loop; "auto" -> every local device
    "max_slots": 8,  # the fixed decode batch width: EVERY decode step runs
    # the one compiled (max_slots, 1) program — sequences join/leave slots
    # per step, the shape never changes, compile storms are structurally
    # impossible on the decode path
    "kv_blocks": 64,  # KV-pool blocks per replica (block 0 is the reserved
    # garbage block, so kv_blocks - 1 are allocatable)
    "kv_block_size": 16,  # tokens per KV block
    "max_seq_len": 128,  # prompt + generated ceiling per sequence (also the
    # position-embedding table length the model must cover)
    "max_new_tokens": 32,  # per-request generation cap (requests may ask
    # for fewer, never more)
    "stop_token": None,  # token id that terminates a sequence when sampled
    # (consumed, not emitted); None -> max_new_tokens is the only terminator
    "temperature": 0.0,  # 0 = greedy argmax; > 0 = softmax sampling with a
    # per-sequence deterministic stream (batch composition cannot change it)
    "max_queue_depth": 256,  # admission control, as the outer serving block
    "per_tenant_quota": None,
    "stats_window": 64,  # generated tokens per decode_stats history row
    # -- survivability knobs (tpuddp/serving/survive.py): same semantics as
    # the outer serving block. A decode replica that dies mid-stream parks
    # its live sequences into host-side session journals; they fail over
    # to a healthy replica (or to this one, once it passes probation) and
    # continue BITWISE-equal to an undisturbed run. No retry_budget here:
    # the failover journal is the decode path's retry mechanism.
    "request_ttl_s": None,  # shed requests still queued this long after
    # submit (deadline_exceeded); in-flight sequences are never killed
    "max_recoveries": 2,  # lifetime probation episodes per decode replica
    "recovery_attempts": 2,  # rebuild-KV-pool + canary tries per episode
    "recovery_backoff_s": 0.1,  # base jittered backoff between tries
    "max_failovers": 1,  # per-SESSION failover episodes, charged only to
    # the attributed CULPRIT of a place-phase incident: past the budget
    # the request fails with the dispatch error instead of re-parking —
    # the poisoned-request firewall (a request whose own content kills
    # any dispatch must not ride its journal around the pool; innocent
    # sessions parked by someone else's incident ride free). 0 = a
    # culprit is never re-parked (legacy stream-dies behavior).
    "seed": 0,  # fresh-init parameter seed (ignored with a checkpoint)
}


def decode_config(serving: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Resolve a resolved serving block's ``decode`` sub-block: ``None``/
    ``False`` -> None (no decode engine), ``True`` -> all defaults, a dict
    -> defaults + overrides with unknown-key refusal."""
    block = serving.get("decode")
    if block is None or block is False:
        return None
    if block is True:
        cfg = dict(DECODE_DEFAULTS)
        cfg, _ = apply_tune_overlay(cfg, section="decode")
        return cfg
    if not isinstance(block, dict):
        raise ValueError(
            f"serving.decode must be a mapping or bool, got {block!r}"
        )
    cfg = _merge_refusing_unknown(DECODE_DEFAULTS, block, "serving.decode")
    cfg, _ = apply_tune_overlay(cfg, section="decode")
    return cfg


# Live telemetry plane knobs (tpuddp/observability/{exporter,aggregate,
# flight}.py) — the ``observability`` block of a settings file, consumed by
# both training entrypoints, the serving engine, and tools/loadgen.py.
# Same unknown-key-refusal contract as the ``training`` block.
OBSERVABILITY_DEFAULTS = {
    "exporter": False,  # opt-in /metrics + /healthz + /snapshot HTTP endpoint
    # (observability/exporter.py): true serves on exporter_host:exporter_port;
    # everything it publishes is host-side state the per-window fence already
    # materialized — no new device fences, HLO untouched
    "exporter_host": "127.0.0.1",  # bind address (0.0.0.0 to scrape off-host)
    "exporter_port": 0,  # 0 = ephemeral; the bound port lands in
    # <out_dir>/exporter.port and the run_meta observability header field
    "aggregate": True,  # multi-host pod aggregation: each host publishes its
    # last-window telemetry shard through the heartbeat-file channel
    # (resilience/watchdog.py line 2); the main process merges shards into
    # pod-level percentiles every window. Inert on single-process runs.
    "straggler_ratio": 1.5,  # a host is straggling when its window step-time
    # p50 exceeds ratio x the pod median ...
    "straggler_windows": 3,  # ... for this many CONSECUTIVE fresh windows —
    # then exactly one typed `straggler` event row lands in history.jsonl
    "flight_recorder": True,  # bounded in-memory ring of the last N history
    # records per kind (step_stats/event/epoch/serving_stats), dumped to
    # flightrec_<reason>.json on abnormal exits (preempt 75 / watchdog 76 /
    # desync 77 / unhandled exception / serving dispatch death)
    "flight_capacity": 64,  # ring length per record kind
    "tracing": False,  # causal tracing plane (observability/trace.py):
    # host-side span trees through training (epoch/stage/dispatch/
    # collective/readback), serving (request/admission/queue_wait/prefill/
    # decode_step + failover links) and the fleet controller, exported as a
    # Perfetto-loadable trace_<role>.json at drain and served live on the
    # exporter's /trace endpoint. Pure host bracketing: zero new device
    # fences, HLO and loss trajectory identical tracing on/off.
    "trace_capacity": 4096,  # completed-span ring length per process
    # (oldest spans dropped past it, counted in the trace_summary record)
    "advisor": False,  # arm the autotuning advisor's crash hook
    # (observability/advisor.py): on preempt/exception the flight recorder
    # dumps the PENDING (unendorsed) knob recommendation over this run dir
    # as a `pending_tune` context block, so a crash never silently discards
    # the evidence that was about to be acted on. Read-only: the advisor
    # never changes a knob itself — applying one is $TPUDDP_TUNE_OVERLAY's
    # job (the fleet tuner / tools/autotune.py), and advisor-off runs are
    # bitwise- and HLO-identical to pre-advisor behavior.
}


# 2-D mesh knobs (tpuddp/parallel/mesh2d.py) — the top-level ``parallel``
# block of a settings file: how the device world factors into the
# ("data", "model") grid. Same unknown-key-refusal contract as every block.
PARALLEL_DEFAULTS = {
    "data": "auto",  # data-parallel width; "auto" -> world_size / model
    "model": 1,  # tensor-parallel width (1 = plain DDP, today's behavior —
    # the 2-D mesh with model=1 collapses to the flat data mesh and lowers
    # to byte-identical HLO). > 1 shards the transformer family's
    # attention/MLP/vocab weights 1/M per chip (parallel/tensor.py).
}


def parallel_config(settings: Dict[str, Any]) -> Dict[str, Any]:
    """Merge the settings file's ``parallel`` block over
    :data:`PARALLEL_DEFAULTS`, refusing unknown keys."""
    return resolve_parallel(settings.get("parallel"))


def resolve_parallel(block) -> Dict[str, Any]:
    """Resolve a ``parallel`` block (None/dict) to the full knob dict.

    ``$TPUDDP_MODEL_SIZE`` overrides the model width the way
    ``$TPUDDP_WORLD_SIZE`` overrides the world: it is the restart
    supervisor's / fleet controller's elastic-mesh lever — a relaunch after
    capacity loss sets both so the child derives ``data = world / model``
    on the surviving devices. The override also resets an explicit ``data``
    to ``"auto"`` (the settings file's factorization was for the OLD world)."""
    if block is None:
        cfg = dict(PARALLEL_DEFAULTS)
    elif not isinstance(block, dict):
        raise ValueError(f"parallel block must be a mapping, got {block!r}")
    else:
        cfg = _merge_refusing_unknown(PARALLEL_DEFAULTS, block, "parallel")
    env_model = os.environ.get("TPUDDP_MODEL_SIZE")
    if env_model:
        cfg["model"] = int(env_model)
        cfg["data"] = "auto"
    model = int(cfg["model"])
    if model < 1:
        raise ValueError(f"parallel.model must be >= 1, got {cfg['model']!r}")
    cfg["model"] = model
    if cfg["data"] != "auto":
        data = int(cfg["data"])
        if data < 1:
            raise ValueError(f"parallel.data must be >= 1 or 'auto', got {cfg['data']!r}")
        cfg["data"] = data
    return cfg


def mesh_from(
    parallel,
    world_size: Optional[int] = None,
    comm_topology: str = "flat",
    devices=None,
    backend: Optional[str] = None,
):
    """Build the run's device mesh from the ``parallel`` block.

    ``model=1`` keeps today's meshes exactly: the flat data mesh, or the
    factored ``("host", "local")`` mesh under ``comm_topology:
    hierarchical``. ``model > 1`` builds the 2-D ``("data", "model")`` grid
    (tpuddp/parallel/mesh2d.py). Refused loudly, never guessed:

    - ``data * model != device_count`` (an explicit ``data`` that does not
      tile the world would silently train a different replica count);
    - ``hierarchical`` + ``model > 1`` (the factored data axis and the model
      axis would need a 3-D mesh the comm hooks do not express).
    """
    from tpuddp.parallel.mesh import data_mesh, hierarchical_mesh, local_mesh_devices
    from tpuddp.parallel.mesh2d import mesh2d

    cfg = resolve_parallel(parallel)
    model = cfg["model"]
    if comm_topology == "hierarchical" and model > 1:
        raise ValueError(
            "parallel.model > 1 with comm_topology='hierarchical' is "
            "refused: pick the 2-D ('data', 'model') mesh OR the factored "
            "('host', 'local') data axis, not both"
        )
    if model == 1 and cfg["data"] == "auto":
        if comm_topology == "hierarchical":
            return hierarchical_mesh(world_size, devices=devices, backend=backend)
        if devices is not None:
            from tpuddp.parallel.mesh import make_mesh

            return make_mesh(devices)
        return data_mesh(world_size, backend)
    if devices is None:
        devices = local_mesh_devices(world_size, backend)
    world = len(devices)
    data = cfg["data"]
    if data == "auto":
        if world % model:
            raise ValueError(
                f"parallel.model={model} does not tile the {world}-device "
                "world; data * model must equal the device count"
            )
        data = world // model
    if data * model != world:
        raise ValueError(
            f"parallel: data={data} x model={model} != device count {world}; "
            "the mesh must tile the world exactly (set data: auto to derive it)"
        )
    if model == 1 and comm_topology == "hierarchical":
        return hierarchical_mesh(world_size, devices=devices, backend=backend)
    return mesh2d(data, model, devices=devices)


def observability_config(settings: Dict[str, Any]) -> Dict[str, Any]:
    """Merge the settings file's ``observability`` block over
    :data:`OBSERVABILITY_DEFAULTS`, refusing unknown keys."""
    return resolve_observability(settings.get("observability"))


def resolve_observability(block) -> Dict[str, Any]:
    """Resolve an ``observability`` block (None/bool/dict) to the full knob
    dict. ``None``/``True`` -> defaults (exporter off, aggregation + flight
    on); ``False`` -> the whole live plane off; a dict overrides the
    defaults with unknown-key refusal. ``exporter`` itself accepts a dict
    (``{host, port}``) as shorthand for the three exporter knobs."""
    if block is None or block is True:
        return dict(OBSERVABILITY_DEFAULTS)
    if block is False:
        off = dict(OBSERVABILITY_DEFAULTS)
        off.update(exporter=False, aggregate=False, flight_recorder=False)
        return off
    if not isinstance(block, dict):
        raise ValueError(
            f"observability block must be a mapping or bool, got {block!r}"
        )
    block = dict(block)
    exporter = block.get("exporter")
    if isinstance(exporter, dict):
        unknown = set(exporter) - {"host", "port"}
        if unknown:
            raise ValueError(
                f"unknown observability.exporter key(s) {sorted(unknown)}; "
                "expected host, port"
            )
        if "host" in exporter:
            block.setdefault("exporter_host", exporter["host"])
        if "port" in exporter:
            block.setdefault("exporter_port", exporter["port"])
        block["exporter"] = True
    return _merge_refusing_unknown(
        OBSERVABILITY_DEFAULTS, block, "observability"
    )


def _merge_refusing_unknown(defaults, overrides, block: str):
    """Defaults + overrides, refusing unknown keys with a did-you-mean hint —
    a typo'd knob silently ignored would run a different configuration than
    the file says. Shared by the ``training`` and ``serving`` blocks."""
    unknown = set(overrides) - set(defaults)
    if unknown:
        import difflib

        hints = []
        for k in sorted(unknown):
            close = difflib.get_close_matches(k, defaults, n=1)
            hints.append(f"{k!r}" + (f" (did you mean {close[0]!r}?)" if close else ""))
        raise ValueError(
            f"unknown {block} key(s): {', '.join(hints)}. Known keys: "
            f"{sorted(defaults)}"
        )
    cfg = dict(defaults)
    cfg.update(overrides)
    return cfg


def serving_config(settings: Dict[str, Any]) -> Dict[str, Any]:
    """Merge the settings file's ``serving`` block over
    :data:`SERVING_DEFAULTS`, refusing unknown keys (the ``training.guard``
    contract). ``$TPUDDP_SERVING_REPLICAS`` overrides ``num_replicas`` the
    way ``$TPUDDP_WORLD_SIZE`` overrides the training world
    (:func:`world_size_from`): the fleet controller resizes a serving job
    by draining it (exit 75) and relaunching the same command with this
    set — one elastic contract for both job kinds. A ``serving`` section of
    ``$TPUDDP_TUNE_OVERLAY`` (the fleet tuner's knob lever) merges last."""
    cfg = _merge_refusing_unknown(
        SERVING_DEFAULTS, settings.get("serving") or {}, "serving"
    )
    env = os.environ.get("TPUDDP_SERVING_REPLICAS")
    if env:
        cfg["num_replicas"] = int(env)
    cfg, _ = apply_tune_overlay(cfg, section="serving")
    return cfg


# ---------------------------------------------------------- tune overlay --
# The fleet tuner's knob lever (tpuddp/tune/online.py): a JSON object in
# this env var carries per-section config diffs plus the provenance fields
# that land in run_meta.tuning. It rides the drain-and-relaunch contract
# the way $TPUDDP_WORLD_SIZE does — the controller mutates the supervisor's
# env and SIGTERMs the child; the relaunch resolves its config THROUGH the
# overlay. Absent env = advisor off = bitwise-identical config resolution.
TUNE_OVERLAY_ENV = "TPUDDP_TUNE_OVERLAY"
_TUNE_OVERLAY_SECTIONS = ("training", "serving", "decode")


def _tune_overlay() -> Optional[Dict[str, Any]]:
    """Parse ``$TPUDDP_TUNE_OVERLAY``; None when unset. A garbled overlay
    refuses loudly — silently training the BASELINE config while run_meta
    claims a tuned one would poison every downstream A/B comparison."""
    raw = os.environ.get(TUNE_OVERLAY_ENV)
    if not raw:
        return None
    import json

    try:
        overlay = json.loads(raw)
    except ValueError as e:
        raise ValueError(f"${TUNE_OVERLAY_ENV} is not valid JSON: {e}")
    if not isinstance(overlay, dict):
        raise ValueError(
            f"${TUNE_OVERLAY_ENV} must be a JSON object, got {overlay!r}"
        )
    unknown = set(overlay) - set(_TUNE_OVERLAY_SECTIONS) - {
        "source", "rule", "generation"
    }
    if unknown:
        raise ValueError(
            f"unknown ${TUNE_OVERLAY_ENV} key(s) {sorted(unknown)}; expected "
            f"sections {_TUNE_OVERLAY_SECTIONS} plus source/rule/generation"
        )
    return overlay


def apply_tune_overlay(
    cfg: Dict[str, Any], section: str = "training"
) -> tuple:
    """Merge ``$TPUDDP_TUNE_OVERLAY``'s ``section`` diff over a RESOLVED
    config dict. Returns ``(config, tuning_provenance)`` — provenance is
    None when no overlay is set (the advisor-off identity path: the input
    dict is returned untouched, not copied). Unknown knobs refuse with the
    config system's did-you-mean contract; dict-valued knobs (pipeline,
    snapshot, guard) merge shallowly so a one-field diff does not clobber
    its siblings."""
    overlay = _tune_overlay()
    if overlay is None:
        return cfg, None
    diff = overlay.get(section) or {}
    if not isinstance(diff, dict):
        raise ValueError(
            f"${TUNE_OVERLAY_ENV}.{section} must be an object, got {diff!r}"
        )
    merged = dict(cfg)
    if diff:
        # knob names validate against the SECTION's full default set, not
        # just the incoming dict — callers hand partial dicts (a worker's
        # hand-built training block) and a knob absent from the partial is
        # still a real knob the overlay may set
        defaults = {
            "training": TRAINING_DEFAULTS,
            "serving": SERVING_DEFAULTS,
            "decode": DECODE_DEFAULTS,
        }.get(section) or {}
        known = set(defaults) | set(cfg)
        unknown = set(diff) - known
        if unknown:
            raise ValueError(
                f"${TUNE_OVERLAY_ENV}.{section} carries unknown knob(s) "
                f"{sorted(unknown)}; known: {sorted(known)}"
            )
        for knob, value in diff.items():
            if isinstance(value, dict) and isinstance(merged.get(knob), dict):
                merged[knob] = {**merged[knob], **value}
            else:
                merged[knob] = value
    return merged, tuning_provenance_from_env(section=section)


def tuning_provenance_from_env(section: str = "training") -> Optional[dict]:
    """The ``run_meta.tuning`` block (schema v12): which overlay this run's
    knobs came from. None (the required key's null value) when no overlay
    is set — a reader must distinguish "human-chosen knobs" from "the fleet
    tuner's generation-N diff"."""
    overlay = _tune_overlay()
    if overlay is None:
        return None
    return {
        "source": overlay.get("source") or "overlay",
        "rule": overlay.get("rule"),
        "generation": overlay.get("generation"),
        "applied": {
            sec: overlay[sec]
            for sec in _TUNE_OVERLAY_SECTIONS
            if isinstance(overlay.get(sec), dict) and overlay[sec]
        },
        "section": section,
    }


# Label-space size by dataset name; the reference hardcodes 10 because its only
# dataset is CIFAR-10 (data_and_toy_model.py:44's Linear(4096, 10)).
DATASET_NUM_CLASSES = {
    "cifar10": 10,
    "synthetic": 10,
    "digits": 10,
}


def num_classes_from(training: Dict[str, Any]) -> int:
    """Head size for the configured dataset: explicit ``training.num_classes``
    wins, else derived from ``training.dataset``."""
    nc = training.get("num_classes")
    if nc is not None:
        return int(nc)
    ds = str(training.get("dataset") or "cifar10")
    if ds not in DATASET_NUM_CLASSES:
        raise ValueError(
            f"cannot derive num_classes for dataset {ds!r}; set "
            "training.num_classes explicitly"
        )
    return DATASET_NUM_CLASSES[ds]


def load_settings(path: str) -> Dict[str, Any]:
    with open(path, "r") as f:
        settings = yaml.safe_load(f)
    if not isinstance(settings, dict):
        raise ValueError(f"settings file {path} did not parse to a mapping")
    return settings


def prepare_out_dir(settings: Dict[str, Any], settings_file: str) -> str:
    """mkdir out_dir + copy the settings file into it for provenance —
    the reference's __main__ ritual (multi-GPU-training-torch.py:293-303)."""
    out_dir = settings["out_dir"]
    os.makedirs(out_dir, exist_ok=True)
    dest = os.path.join(out_dir, os.path.basename(settings_file))
    if os.path.abspath(dest) != os.path.abspath(settings_file):
        with open(dest, "w") as f:
            yaml.dump(settings, f)
    return out_dir


def world_size_from(settings: Dict[str, Any]) -> Optional[int]:
    """World size: ``$TPUDDP_WORLD_SIZE`` (the restart supervisor's elastic
    override — tools/supervise.py shrinks a repeatedly-dying world by
    re-launching the same command with this set), else ``local.tpu.num_chips``
    (TPU-native) or the reference's ``local.condor.num_gpus`` (:306).
    None -> all local devices."""
    env = os.environ.get("TPUDDP_WORLD_SIZE")
    if env:
        return int(env)
    local = settings.get("local", {})
    if "tpu" in local and "num_chips" in local["tpu"]:
        return int(local["tpu"]["num_chips"])
    if "condor" in local and "num_gpus" in local["condor"]:
        return int(local["condor"]["num_gpus"])
    return None


def device_from(settings: Dict[str, Any]) -> Optional[str]:
    """``local.device``: 'tpu' or 'cpu' (the dev/test rung). Maps onto the
    backend ladder's prefer argument."""
    dev = settings.get("local", {}).get("device")
    if dev in (None, "tpu", "cpu"):
        return dev
    if dev == "cuda":
        # GPU settings files from the reference keep working: on a TPU host the
        # ladder resolves to tpu, elsewhere to cpu.
        return None
    raise ValueError(f"unsupported local.device {dev!r} (expected tpu or cpu)")


def rendezvous_from(settings: Dict[str, Any]) -> Dict[str, Any]:
    """``local.rendezvous`` block -> kwargs for ``run_ddp_training``.

    The TPU-native analog of the reference's ``MASTER_ADDR``/``MASTER_PORT``
    rendezvous (multi-GPU-training-torch.py:30-31): ``coordinator_address``
    ("host:port"), ``num_processes``, ``process_id``. Environment overrides
    ``TPUDDP_COORDINATOR`` / ``TPUDDP_NUM_PROCESSES`` / ``TPUDDP_PROCESS_ID``
    let one shared settings file serve every host of a pod — the launcher sets
    the per-host process id in the environment, exactly as torchrun exports
    RANK alongside a shared MASTER_ADDR.
    """
    rdv = dict(settings.get("local", {}).get("rendezvous") or {})
    env = os.environ
    if env.get("TPUDDP_COORDINATOR"):
        rdv["coordinator_address"] = env["TPUDDP_COORDINATOR"]
    if env.get("TPUDDP_NUM_PROCESSES"):
        rdv["num_processes"] = env["TPUDDP_NUM_PROCESSES"]
    if env.get("TPUDDP_PROCESS_ID"):
        rdv["process_id"] = env["TPUDDP_PROCESS_ID"]

    out: Dict[str, Any] = {}
    if rdv.get("coordinator_address"):
        out["coordinator_address"] = str(rdv["coordinator_address"])
    if rdv.get("num_processes") is not None:
        out["num_processes"] = int(rdv["num_processes"])
    if rdv.get("process_id") is not None:
        out["process_id"] = int(rdv["process_id"])
    unknown = set(rdv) - {"coordinator_address", "num_processes", "process_id"}
    if unknown:
        raise ValueError(
            f"unknown local.rendezvous keys {sorted(unknown)}; expected "
            "coordinator_address, num_processes, process_id"
        )
    if out.get("num_processes", 1) > 1:
        if not out.get("coordinator_address") and device_from(settings) != "tpu":
            # Only TPU pods can auto-discover peers (initialize() reads the
            # pod environment; set local.device: tpu to use that). Anywhere
            # else — cpu, unset, or a migrated cuda settings file — a missing
            # coordinator would skip the dev re-exec (which gates on it) yet
            # still reach jax.distributed.initialize(None, ...), dying late
            # with an obscure runtime error; fail clearly here instead.
            raise ValueError(
                "local.rendezvous with num_processes > 1 needs a "
                "coordinator_address (host:port of process 0; set "
                "TPUDDP_COORDINATOR or the YAML key) — or local.device: tpu "
                "to use TPU pod auto-discovery"
            )
        if out.get("coordinator_address") and "process_id" not in out:
            raise ValueError(
                "local.rendezvous with num_processes > 1 needs a process_id "
                "(set TPUDDP_PROCESS_ID per host, or the YAML key)"
            )
    return out


def optional_args_from(settings: Dict[str, Any]) -> Dict[str, Any]:
    return dict(settings.get("optional_args") or {})


OPTIMIZERS = ("adam", "sgd", "sgdw", "lars", "lamb")


def optimizer_from(training: Dict[str, Any]):
    """Build the configured optimizer (``training.optimizer``) — ONE factory
    for both entrypoints, so the knob set and defaults cannot drift between
    the native and managed paths. ``adam`` keeps the reference's exact
    construction (lr + optional bf16 moment storage); the large-batch
    optimizers take the decoupled ``weight_decay`` / ``momentum`` /
    ``trust_coefficient`` knobs (LARS/LAMB per-layer trust ratios,
    tpuddp/optim.py)."""
    from tpuddp import optim

    name = str(training.get("optimizer") or "adam").lower()
    lr = training["learning_rate"]
    wd = float(training.get("weight_decay") or 0.0)
    momentum = float(
        training["momentum"] if training.get("momentum") is not None else 0.9
    )
    if name == "adam":
        return optim.Adam(
            lr=lr,
            weight_decay=wd,
            state_dtype=training.get("optimizer_state_dtype"),
        )
    if training.get("optimizer_state_dtype"):
        raise ValueError(
            "training.optimizer_state_dtype is an Adam knob (bf16 moment "
            f"storage); optimizer {name!r} stores its state in f32"
        )
    if name == "sgd":
        return optim.SGD(lr, momentum=momentum, weight_decay=wd)
    if name == "sgdw":
        return optim.SGDW(lr, momentum=momentum, weight_decay=wd)
    if name == "lars":
        return optim.LARS(
            lr, momentum=momentum, weight_decay=wd,
            trust_coefficient=float(
                training.get("trust_coefficient") or 0.001
            ),
        )
    if name == "lamb":
        return optim.LAMB(lr, weight_decay=wd)
    raise ValueError(
        f"unknown training.optimizer {name!r}; one of {OPTIMIZERS}"
    )


def training_config(settings: Dict[str, Any]) -> Dict[str, Any]:
    """Merge the settings file's ``training`` block over the defaults.
    Unknown keys are REFUSED with a did-you-mean hint — a typo'd knob
    (``wieght_update_sharding``) silently ignored would train a different
    configuration than the file says."""
    cfg = _merge_refusing_unknown(
        TRAINING_DEFAULTS, settings.get("training") or {}, "training"
    )
    cfg, _ = apply_tune_overlay(cfg, section="training")
    return cfg
