"""Shared shape-bucketing and static-batch padding helpers.

One implementation for every consumer that turns ragged host data into the
static device shapes XLA compiles once:

- the data loaders pad final partial batches (:func:`pad_batch` — the 0/1
  sample-weight convention consumed by the masked loss/metric math and
  BatchNorm, tpuddp/data/loader.py);
- the managed ``FusedEvaluator`` and train-side ``fuse_steps="auto"`` key
  their queues and depth caps by :func:`shape_key` / :func:`resolve_fuse`
  (tpuddp/accelerate.py);
- the native epoch driver's ``scan_steps: auto`` caps its staged super-chunk
  by the same :data:`STAGE_BYTES_BUDGET` (tpuddp/training/loop.py);
- the serving scheduler coalesces variable-size requests into
  power-of-two-bucketed padded batches (:func:`bucket_for`,
  tpuddp/serving/scheduler.py) so the compile cache stays warm: at most
  ``log2(max_batch) + 1`` programs per sample shape, compile storms by
  construction impossible.

These used to live as private helpers inside their consumers; serving made a
second copy inevitable, so they were lifted here instead of diverging.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

# Bound on one staged (K, batch, ...) chunk / one K-deep device queue. The
# number every auto depth policy caps against (BASELINE.md "Dispatch-RTT
# variance" measured depth as the amortization lever; this budget is what
# keeps depth from staging past HBM).
STAGE_BYTES_BUDGET = 256 * 1024 * 1024


def shape_key(x) -> Tuple[Tuple[int, ...], str]:
    """Bucketing key of a batch: (shape, dtype-string). Metadata-only — never
    converts ``x`` (it may be a staged device array; ``np.asarray`` on it
    would force a host transfer)."""
    return (tuple(np.shape(x)), str(getattr(x, "dtype", "untyped")))


def resolve_fuse(batch_nbytes: Optional[int], cap: int = 32) -> int:
    """Depth of a device-side batch queue: ``cap``, bounded by the staging
    budget over one batch's input bytes when they are known — the queue holds
    K such batches on device before each flush, so depth x batch bytes is
    real HBM."""
    cap = max(1, int(cap))
    if batch_nbytes:
        cap = max(1, min(cap, STAGE_BYTES_BUDGET // int(batch_nbytes)))
    return cap


def pad_batch(x: np.ndarray, y: Optional[np.ndarray], batch_size: int):
    """Pad ``(x, y)`` along axis 0 to the static ``batch_size``; returns
    ``(x, y, w)`` where the 0/1 float32 weight vector ``w`` marks real rows.
    Padding repeats row 0 (a real sample, so no NaN/denormal surprises reach
    the compiled program) and zero-labels it; every masked consumer (loss,
    metrics, BatchNorm, the serving scheduler's row slicing) ignores w==0
    rows. ``y=None`` (an unlabeled inference batch) pads x alone and returns
    ``y=None``."""
    n = len(x) if y is None else len(y)
    if n > batch_size:
        raise ValueError(f"batch of {n} rows cannot pad down to {batch_size}")
    w = np.ones(batch_size, np.float32)
    if n < batch_size:
        pad = batch_size - n
        x = np.concatenate([x, np.repeat(x[:1], pad, axis=0)])
        if y is not None:
            y = np.concatenate([y, np.zeros(pad, y.dtype)])
        w[n:] = 0.0
    return x, y, w


def bucket_sizes(max_batch: int):
    """The power-of-two ladder up to ``max_batch`` (inclusive; ``max_batch``
    itself is always the top rung even when it is not a power of two)."""
    max_batch = int(max_batch)
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    sizes = []
    b = 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return sizes


def bucket_for(n: int, max_batch: int) -> int:
    """Smallest bucket that holds ``n`` rows. Bounds the set of compiled
    batch shapes: every dispatched batch is one of :func:`bucket_sizes`."""
    if n < 1:
        raise ValueError(f"cannot bucket {n} rows")
    if n > max_batch:
        raise ValueError(f"{n} rows exceed max_batch={max_batch}")
    for b in bucket_sizes(max_batch):
        if n <= b:
            return b
    return max_batch  # unreachable: the ladder always ends at max_batch
