"""Utility subsystems: compat shims, debugging. (Observability graduated to
the ``tpuddp.observability`` package; the re-exports below keep old import
paths working.)"""

from tpuddp.utils.observability import (  # noqa: F401
    MetricsWriter,
    check_finite,
    maybe_start_profiler,
    stop_profiler,
)

__all__ = ["MetricsWriter", "check_finite", "maybe_start_profiler", "stop_profiler"]
