"""Utility subsystems: observability (tracing/profiling/metrics), debugging."""

from tpuddp.utils.observability import (  # noqa: F401
    MetricsWriter,
    check_finite,
    maybe_start_profiler,
    stop_profiler,
)

__all__ = ["MetricsWriter", "check_finite", "maybe_start_profiler", "stop_profiler"]
