"""Compatibility shim — the observability subsystem moved to
``tpuddp.observability`` (a real package: typed record schema, step-level
telemetry recorder, on-demand profiling, strict-JSON writers). This module
re-exports the original names so pre-existing imports keep working; new code
should import from :mod:`tpuddp.observability` directly."""

from tpuddp.observability import (  # noqa: F401
    CommBytesCounter,
    MetricsWriter,
    check_finite,
    json_sanitize,
    maybe_start_profiler,
    nan_checks_enabled,
    stop_profiler,
)

__all__ = [
    "CommBytesCounter",
    "MetricsWriter",
    "check_finite",
    "json_sanitize",
    "maybe_start_profiler",
    "nan_checks_enabled",
    "stop_profiler",
]
