"""Observability — the auxiliary subsystems the reference only gestures at
(SURVEY.md §5):

- **Tracing/profiling**: the reference's only hook is a commented-out
  ``NCCL_DEBUG=INFO`` env knob (multi-GPU-training-torch.py:8-10). tpuddp's
  analog is env-toggled XLA profiling: ``TPUDDP_PROFILE=<dir>`` starts a
  ``jax.profiler`` trace (viewable in TensorBoard/XProf, captures HLO +
  TPU step events) for the first epoch.
- **NaN detection**: ``TPUDDP_DEBUG_NANS=1`` makes the epoch driver raise on
  non-finite aggregated losses (the "race detection / sanitizer" row of
  SURVEY.md §5 — JAX's functional purity removes data races; numerical blowup
  is the failure mode worth a guard). The epoch driver fires it BEFORE any
  checkpoint save, so a poisoned epoch can never persist its state. The
  in-step complement — skipping the poisoned update itself — is the
  ``training.guard`` firewall (tpuddp/resilience/guard.py).
- **Metrics**: per-epoch JSONL history written by process 0 next to the
  checkpoints, replacing grep-able stdout as the machine-readable record
  (condor .out parsing in the reference, submit_job.py:36-38).
- **Comm-bytes accounting**: :class:`CommBytesCounter` turns the static
  per-update gradient-communication payload (parallel/comm.py's accounting
  model — the operand bytes entering the gradient collective, in its wire
  dtype) into a running per-epoch/cumulative counter, so a compressed
  comm hook's byte reduction is a recorded artifact in ``history.jsonl``
  and the bench output, not a claim.
"""

from __future__ import annotations

import json
import math
import os
from typing import Optional

import jax

_PROFILE_ENV = "TPUDDP_PROFILE"
_NANS_ENV = "TPUDDP_DEBUG_NANS"
_profiling = {"active": False}


def maybe_start_profiler(default_dir: Optional[str] = None) -> bool:
    """Start an XLA trace if $TPUDDP_PROFILE is set (its value is the trace
    dir; '1' falls back to ``default_dir``/trace). Returns True if started."""
    target = os.environ.get(_PROFILE_ENV)
    if not target or _profiling["active"]:
        return False
    if target == "1":
        if default_dir is None:
            return False
        target = os.path.join(default_dir, "trace")
    os.makedirs(target, exist_ok=True)
    jax.profiler.start_trace(target)
    _profiling["active"] = True
    return True


def stop_profiler() -> None:
    if _profiling["active"]:
        jax.profiler.stop_trace()
        _profiling["active"] = False


def nan_checks_enabled() -> bool:
    return os.environ.get(_NANS_ENV, "") not in ("", "0")


def json_sanitize(value):
    """Strict-JSON form of a record: non-finite floats become ``None``
    (serialized ``null``), recursively through dicts/lists/tuples.

    Python's ``json.dumps`` default emits bare ``NaN``/``Infinity`` tokens —
    *invalid* JSON that strict parsers (jq, serde, JSON.parse, BigQuery
    loads) reject, which made ``history.jsonl`` and ``bench_results.json``
    unconsumable the moment an epoch blew up (the empty-test-loader path
    writes ``float("nan")`` test metrics by design). Writers here pair this
    with ``json.dumps(..., allow_nan=False)`` so any future non-finite leak
    fails loudly at write time instead of corrupting the artifact."""
    if isinstance(value, dict):
        return {k: json_sanitize(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [json_sanitize(v) for v in value]
    if isinstance(value, float) and not math.isfinite(value):
        return None
    return value


def check_finite(value: float, what: str) -> None:
    """Raise if a host-side aggregated metric went non-finite (only when
    $TPUDDP_DEBUG_NANS is set)."""
    if nan_checks_enabled() and not math.isfinite(value):
        raise FloatingPointError(f"non-finite {what}: {value}")


class CommBytesCounter:
    """Running gradient-communication byte counter (per replica).

    The per-update payload is static (compiled into the step program), so the
    counter is host-side multiplication — free next to a device step. ``None``
    bytes-per-update (a ddp object predating init_state, or an Accelerator
    facade without the attribute) degrades to an inert counter whose
    :meth:`snapshot` returns ``{}`` so epoch records stay unchanged.
    """

    def __init__(self, bytes_per_update):
        self.bytes_per_update = (
            int(bytes_per_update) if bytes_per_update else None
        )
        self.updates = 0

    def add_updates(self, n: int) -> None:
        self.updates += int(n)

    @property
    def total_bytes(self):
        if self.bytes_per_update is None:
            return None
        return self.bytes_per_update * self.updates

    def snapshot(self, epoch_updates: int = None) -> dict:
        """Record fields for the JSONL history: the static per-update payload,
        the cumulative total, and (when given) this epoch's slice."""
        if self.bytes_per_update is None:
            return {}
        out = {
            "grad_comm_bytes_per_update": self.bytes_per_update,
            "grad_comm_bytes_total": self.total_bytes,
        }
        if epoch_updates is not None:
            out["grad_comm_bytes_epoch"] = self.bytes_per_update * int(epoch_updates)
        return out


class MetricsWriter:
    """Process-0 JSONL metrics sink (``history.jsonl`` in the run dir).

    Holds one append handle (opened lazily at the first record) and flushes
    after every line, so the file always ends on a whole JSON record — a crash
    or preemption mid-epoch must not truncate the machine-readable history.
    The epoch driver calls :meth:`close` from its ``finally`` block."""

    def __init__(self, save_dir: Optional[str], filename: str = "history.jsonl"):
        self.path = None
        self._f = None
        if save_dir is not None and jax.process_index() == 0:
            os.makedirs(save_dir, exist_ok=True)
            self.path = os.path.join(save_dir, filename)

    def write(self, record: dict) -> None:
        if self.path is None:
            return
        if self._f is None:
            self._f = open(self.path, "a")
        # strict JSON on disk: NaN/Inf metrics (a blown-up epoch's
        # post-mortem row) serialize as null, never as the bare NaN token
        # strict parsers reject
        self._f.write(json.dumps(json_sanitize(record), allow_nan=False) + "\n")
        self._f.flush()

    def flush(self) -> None:
        if self._f is not None:
            self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None

    def __del__(self):  # backstop for callers that never reach close()
        try:
            self.close()
        except Exception:
            pass
