"""JAX version-compatibility shims.

The framework targets the current ``jax.shard_map`` API; older runtimes (< 0.6)
only ship ``jax.experimental.shard_map.shard_map`` with the pre-rename
``check_rep`` keyword (renamed ``check_vma`` when the API stabilized). One
resolution point here keeps every call site on the modern spelling — the
robustness analog of stubbing a missing dep instead of crashing at import.
"""

from __future__ import annotations

import jax
from jax import lax
from jax.sharding import PartitionSpec as _P

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kwargs):
        if in_specs is None:
            # modern API: None = every input replicated; the experimental one
            # wants PartitionSpec pytrees (P() is the all-replicated prefix)
            in_specs = _P()
        return _experimental_shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
            **kwargs,
        )


if hasattr(lax, "axis_size"):
    axis_size = lax.axis_size
else:

    def axis_size(axis_name):
        # pre-axis_size spelling: a psum of 1 over the axis; XLA folds it to a
        # compile-time constant, so this costs nothing at runtime
        return lax.psum(1, axis_name)
