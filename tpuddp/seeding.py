"""Rank-aware seeding — parity with reference ``set_seed_based_on_rank``
(multi-GPU-training-torch.py:54-69).

The reference derives each process's seeds from ``torch.initial_seed()`` (which
``mp.spawn`` randomizes per run and varies per rank), re-seeding torch at
``initial + rank`` and Python/NumPy at ``initial % (2**32 - 1) + rank`` — the
deliberately different seed range quirk is preserved here.

The JAX-native analog is a single base seed folded with the process index into
a ``jax.random`` key; *device-level* divergence (e.g. per-replica dropout) is
done inside jit by folding in ``lax.axis_index`` — see
:func:`fold_in_axis_index`. ``cudnn.deterministic`` (reference :63-64) has no
TPU knob: XLA on TPU is deterministic by default; we log for API parity.
"""

from __future__ import annotations

import logging
import os
import random
import struct
from typing import Optional, Tuple

import jax
import numpy as np
from jax import lax

logger = logging.getLogger("tpuddp")

_last_base_seed: Optional[int] = None


def initial_seed() -> int:
    """A fresh random base seed (analog of torch's per-run ``initial_seed``)."""
    return struct.unpack("<Q", os.urandom(8))[0] >> 1  # non-negative int64


def set_seed_based_on_rank(
    rank: Optional[int] = None, base_seed: Optional[int] = None
) -> Tuple[jax.Array, int]:
    """Seed Python/NumPy and derive this process's JAX PRNG key.

    Returns ``(key, base_seed)``. Pass the returned ``base_seed`` to other
    processes (or set it in config) so ranks differ only by the fold. With
    ``base_seed=None`` a fresh one is drawn per run, like torch's initial seed.
    """
    global _last_base_seed
    if rank is None:
        rank = jax.process_index()
    if base_seed is None:
        base_seed = initial_seed()
    _last_base_seed = base_seed

    # JAX side: fold the rank into the base key (analog of torch.manual_seed(initial + rank)).
    key = jax.random.fold_in(jax.random.key(base_seed % (2**63)), rank)

    # Python/NumPy side: reduced seed range + rank, exactly the reference quirk.
    reduced_seed = int(base_seed) % (2**32 - 1)
    random.seed(reduced_seed + rank)
    np.random.seed((reduced_seed + rank) % (2**32))

    # Reference sets cudnn.deterministic=True here; XLA/TPU is deterministic by
    # default, so this is a logged no-op kept for API parity (SURVEY.md §2b #17).
    logger.debug("deterministic execution: XLA/TPU default (no cudnn knob needed)")
    return key, base_seed


def last_base_seed() -> Optional[int]:
    """The base seed from the most recent set_seed_based_on_rank call — the
    analog of ``torch.initial_seed()`` for the print_rand debug probe
    (multi-GPU-training-torch.py:180-183)."""
    return _last_base_seed


def rng_probe_string() -> str:
    """Formatted RNG-state dump matching the reference's print_rand probe."""
    py_state = random.getstate()[1][:3]
    np_state = np.random.get_state()[1][:3]
    return (
        f"Python random state: {py_state}, numpy random state: {tuple(np_state)}; "
        f"base seed: {_last_base_seed}"
    )


def fold_in_axis_index(key: jax.Array, axis_name: str = "data") -> jax.Array:
    """Inside shard_map/pmap: derive a per-replica key (device-level rank fold),
    so e.g. dropout masks differ across replicas."""
    return jax.random.fold_in(key, lax.axis_index(axis_name))
